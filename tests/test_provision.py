"""Provision layer tests: dispatch, local provider end-to-end, GCP
error-mapping (mocked HTTP).

Reference test analog: the reference has no provisioner unit tests (it
relies on smoke tests, SURVEY.md §4.4); the local provider makes this
layer testable offline.
"""
import os
import time

import pytest
import requests

from skypilot_tpu import provision
from skypilot_tpu.provision import common
from skypilot_tpu.provision import provisioner


def test_dispatch_unknown_provider():
    with pytest.raises(ValueError, match='Unknown provision provider'):
        provision.query_instances('nope', 'c', {})


@pytest.fixture()
def local_cluster(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))
    name = 'prov-test'
    cfg = common.ProvisionConfig(provider_name='local', region='local',
                                 zone=None, cluster_name=name, num_nodes=2)
    yield name, cfg
    provisioner.teardown_cluster('local', name, {}, terminate=True)


def _wait_job(port, jid, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = requests.get(f'http://127.0.0.1:{port}/jobs/{jid}',
                          timeout=5).json()
        if st['status'] in ('SUCCEEDED', 'FAILED', 'FAILED_SETUP',
                            'CANCELLED'):
            return st
        time.sleep(0.3)
    raise TimeoutError(f'job {jid} did not finish')


def test_local_provision_gang_job(local_cluster):
    name, cfg = local_cluster
    record = provisioner.bulk_provision('local', cfg)
    assert record.head_instance_id == f'{name}-host-0'
    assert len(record.created_instance_ids) == 2

    statuses = provision.query_instances('local', name, {})
    assert all(s == 'running' for s in statuses.values())

    info = provision.get_cluster_info('local', 'local', name,
                                      cfg.provider_config)
    assert info.num_instances() == 2
    port = info.provider_config['head_port']

    # Gang job across both "hosts" with the rank/env contract.
    resp = requests.post(
        f'http://127.0.0.1:{port}/jobs/submit',
        json={'spec': {'name': 'hello', 'num_nodes': 2, 'envs': {},
                       'run': 'echo rank $SKYT_NODE_RANK '
                              'coord $SKYT_COORDINATOR_ADDRESS'}},
        timeout=5)
    jid = resp.json()['job_id']
    st = _wait_job(port, jid)
    assert st['status'] == 'SUCCEEDED'
    assert len(st['gang']) == 2
    assert all(g['returncode'] == 0 for g in st['gang'])

    # Both ranks wrote logs in their own host dir.
    root = os.environ['SKYT_LOCAL_ROOT']
    for rank in range(2):
        log = os.path.join(root, name, f'host-{rank}', '.skyt', 'logs',
                           str(jid), f'rank-{rank}.log')
        content = open(log, encoding='utf-8').read()
        assert f'rank {rank}' in content

    # Idempotent re-provision resumes, not creates.
    record2 = provisioner.bulk_provision('local', cfg)
    assert record2.created_instance_ids == []
    assert len(record2.resumed_instance_ids) == 2


def test_local_stop_and_terminate(local_cluster):
    name, cfg = local_cluster
    provisioner.bulk_provision('local', cfg)
    provision.stop_instances('local', name, {})
    statuses = provision.query_instances('local', name, {})
    assert all(s == 'stopped' for s in statuses.values())
    provision.terminate_instances('local', name, {})
    assert provision.query_instances('local', name, {}) == {}


# ----------------------------------------------------------------- GCP
class _FakeResp:
    def __init__(self, status, payload):
        self.status_code = status
        self._payload = payload
        self.content = b'x'
        self.text = str(payload)

    def json(self):
        return self._payload


def _fake_session(responses):
    """responses: list of (method, path_substr, status, payload)."""
    calls = []

    class _Sess:
        def request(self, method, url, **kwargs):
            calls.append((method, url))
            for m, sub, status, payload in responses:
                if m == method and sub in url:
                    return _FakeResp(status, payload)
            return _FakeResp(404, {'error': {'message': 'not found'}})

    return _Sess, calls


def test_gcp_capacity_error_blocks_zone(monkeypatch):
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    sess, _ = _fake_session([
        ('GET', '/nodes/c1', 404, {'error': {'message': 'not found'}}),
        ('POST', '/queuedResources', 429,
         {'error': {'message': 'There is no more capacity in the zone'}}),
    ])
    monkeypatch.setattr(tpu_api, '_session', sess)
    cfg = common.ProvisionConfig(
        provider_name='gcp', region='us-central2', zone='us-central2-b',
        cluster_name='c1', num_nodes=4,
        node_config={'accelerator_type': 'v4-32'},
        provider_config={'project': 'p', 'availability_zone':
                         'us-central2-b'})
    with pytest.raises(common.ProvisionError) as exc:
        gcp_instance.run_instances(cfg)
    assert exc.value.blocked_zone == 'us-central2-b'


def test_gcp_quota_error_blocks_region(monkeypatch):
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    sess, _ = _fake_session([
        ('GET', '/nodes/c1', 404, {'error': {'message': 'not found'}}),
        ('POST', '/queuedResources', 403,
         {'error': {'message': 'Quota exceeded for TPU v5e cores'}}),
    ])
    monkeypatch.setattr(tpu_api, '_session', sess)
    cfg = common.ProvisionConfig(
        provider_name='gcp', region='us-west4', zone='us-west4-a',
        cluster_name='c1', num_nodes=4,
        node_config={'accelerator_type': 'v5litepod-16'},
        provider_config={'project': 'p', 'availability_zone': 'us-west4-a'})
    with pytest.raises(common.ProvisionError) as exc:
        gcp_instance.run_instances(cfg)
    assert exc.value.blocked_region == '*'


def test_gcp_queued_resource_body(monkeypatch):
    """The queued-resource request carries the pod-slice node spec."""
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    bodies = {}

    class _Sess:
        def request(self, method, url, data=None, **kwargs):
            if method == 'GET' and '/nodes/' in url:
                return _FakeResp(404, {'error': {'message': 'nf'}})
            if method == 'POST' and '/queuedResources' in url:
                import json as _json
                bodies.update(_json.loads(data))
                return _FakeResp(200, {'name': 'op/1'})
            return _FakeResp(404, {'error': {'message': 'nf'}})

    monkeypatch.setattr(tpu_api, '_session', lambda: _Sess())
    cfg = common.ProvisionConfig(
        provider_name='gcp', region='us-west4', zone='us-west4-a',
        cluster_name='tr-16', num_nodes=4,
        node_config={'accelerator_type': 'v5litepod-16', 'spot': True,
                     'runtime_version': 'v2-alpha-tpuv5-lite',
                     'ssh_public_key': 'ssh-ed25519 AAAA test'},
        provider_config={'project': 'p', 'availability_zone': 'us-west4-a'})
    record = gcp_instance.run_instances(cfg)
    assert record.created_instance_ids == [
        f'tr-16-host-{r}' for r in range(4)]
    assert record.head_instance_id == 'tr-16-host-0'
    assert 'spot' in bodies
    node = bodies['tpu']['nodeSpec'][0]['node']
    assert node['acceleratorType'] == 'v5litepod-16'
    assert node['schedulingConfig']['preemptible'] is True
    assert 'ssh-keys' in node['metadata']


def test_gcp_state_mapping(monkeypatch):
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    sess, _ = _fake_session([
        ('GET', '/nodes/c1', 200, {'state': 'PREEMPTED'}),
    ])
    monkeypatch.setattr(tpu_api, '_session', sess)
    out = gcp_instance.query_instances(
        'c1', {'project': 'p', 'availability_zone': 'z'})
    # Per-host id namespace, matching get_cluster_info / local provider.
    assert out == {'c1-host-0': 'terminated'}


def test_gcp_cluster_info_ranks(monkeypatch):
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    sess, _ = _fake_session([
        ('GET', '/nodes/pod', 200, {
            'state': 'READY',
            'networkEndpoints': [
                {'ipAddress': '10.0.0.2',
                 'accessConfig': {'externalIp': '34.1.1.2'}},
                {'ipAddress': '10.0.0.3',
                 'accessConfig': {'externalIp': '34.1.1.3'}},
            ]}),
    ])
    monkeypatch.setattr(tpu_api, '_session', sess)
    info = gcp_instance.get_cluster_info(
        'us-west4', 'pod', {'project': 'p', 'availability_zone': 'z',
                            'ssh_user': 'me'})
    assert info.internal_ips() == ['10.0.0.2', '10.0.0.3']
    assert info.external_ips() == ['34.1.1.2', '34.1.1.3']
    assert info.head_instance_id == 'pod-host-0'


def test_gcp_multislice_queued_resource_body(monkeypatch):
    """num_slices=2: ONE queued resource, TWO nodeSpec entries (atomic
    cross-slice gang), per-slice node ids."""
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    bodies = {}

    class _Sess:
        def request(self, method, url, data=None, **kwargs):
            if method == 'GET' and '/nodes/' in url:
                return _FakeResp(404, {'error': {'message': 'nf'}})
            if method == 'POST' and '/queuedResources' in url:
                import json as _json
                bodies.update(_json.loads(data))
                return _FakeResp(200, {'name': 'op/1'})
            return _FakeResp(404, {'error': {'message': 'nf'}})

    monkeypatch.setattr(tpu_api, '_session', lambda: _Sess())
    cfg = common.ProvisionConfig(
        provider_name='gcp', region='us-west4', zone='us-west4-a',
        cluster_name='ms', num_nodes=8,
        node_config={'accelerator_type': 'v5litepod-16', 'spot': False,
                     'runtime_version': 'v2-alpha-tpuv5-lite',
                     'ssh_public_key': 'ssh-ed25519 AAAA test',
                     'num_slices': 2, 'hosts_per_slice': 4},
        provider_config={'project': 'p', 'availability_zone': 'us-west4-a'})
    record = gcp_instance.run_instances(cfg)
    specs = bodies['tpu']['nodeSpec']
    assert [s['nodeId'] for s in specs] == ['ms-s0', 'ms-s1']
    assert all(s['node']['acceleratorType'] == 'v5litepod-16'
               for s in specs)
    assert record.created_instance_ids == [
        f'ms-host-{r}' for r in range(8)]
    # The slice count rides provider_config for downstream entry points.
    assert cfg.provider_config['num_slices'] == 2


def test_gcp_multislice_cluster_info_slice_major(monkeypatch):
    """get_cluster_info aggregates both slice nodes' endpoints in
    slice-major rank order — the contiguous-group contract gang.py
    splits MEGASCALE slices by."""
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    sess, _ = _fake_session([
        ('GET', '/nodes/ms-s0', 200, {
            'state': 'READY',
            'networkEndpoints': [{'ipAddress': '10.0.0.2'},
                                 {'ipAddress': '10.0.0.3'}]}),
        ('GET', '/nodes/ms-s1', 200, {
            'state': 'READY',
            'networkEndpoints': [{'ipAddress': '10.0.1.2'},
                                 {'ipAddress': '10.0.1.3'}]}),
    ])
    monkeypatch.setattr(tpu_api, '_session', sess)
    info = gcp_instance.get_cluster_info(
        'us-west4', 'ms', {'project': 'p', 'availability_zone': 'z',
                           'num_slices': 2})
    assert info.internal_ips() == ['10.0.0.2', '10.0.0.3',
                                   '10.0.1.2', '10.0.1.3']
    assert info.head_instance_id == 'ms-host-0'

    out = gcp_instance.query_instances(
        'ms', {'project': 'p', 'availability_zone': 'z',
               'num_slices': 2})
    assert len(out) == 4 and set(out.values()) == {'running'}


def test_resources_num_slices():
    from skypilot_tpu import exceptions as exc
    from skypilot_tpu import resources as res_lib

    r = res_lib.Resources(accelerators='tpu-v5e-16', num_slices=2)
    assert r.hosts_per_slice == 4 and r.num_hosts == 8
    assert 'x2slices' in str(r)
    r2 = res_lib.Resources.from_yaml_config(r.to_yaml_config())
    assert r2.num_slices == 2 and r2.num_hosts == 8
    # non-TPU multislice is rejected
    with pytest.raises(exc.InvalidResourcesError, match='num_slices'):
        res_lib.Resources(cloud='local', num_slices=2)
    with pytest.raises(exc.InvalidResourcesError, match='num_slices'):
        res_lib.Resources(accelerators='tpu-v5e-8', num_slices=0)


def test_task_num_nodes_multislice():
    import skypilot_tpu as sky
    from skypilot_tpu import resources as res_lib

    t = sky.Task(name='ms', run='echo hi')
    t.set_resources(res_lib.Resources(accelerators='tpu-v5e-16',
                                      num_slices=2))
    assert t.num_nodes == 8


def test_gcp_multislice_wait_requires_all_slices_ready(monkeypatch):
    """wait_instances must poll every slice node, not the bare cluster
    name (which never exists for multislice), and return only when ALL
    slices are READY."""
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    states = {'ms-s0': iter(['READY', 'READY']),
              'ms-s1': iter(['CREATING', 'READY'])}
    calls = []

    class _Sess:
        def request(self, method, url, data=None, **kwargs):
            calls.append(url)
            if '/queuedResources/' in url:
                return _FakeResp(200, {'state': {'state': 'ACTIVE'}})
            for nid, it in states.items():
                if url.endswith(f'/nodes/{nid}'):
                    return _FakeResp(200, {'state': next(it)})
            return _FakeResp(404, {'error': {'message': 'nf'}})

    monkeypatch.setattr(tpu_api, '_session', lambda: _Sess())
    monkeypatch.setattr('time.sleep', lambda s: None)
    gcp_instance.wait_instances(
        'us-west4', 'ms', state='running',
        provider_config={'project': 'p', 'availability_zone': 'z',
                         'num_slices': 2}, timeout=30)
    # Second poll round saw both READY; the bare 'ms' node was never
    # queried.
    assert not any(u.endswith('/nodes/ms') for u in calls)


def test_gcp_multislice_query_stable_ranks_while_creating(monkeypatch):
    """A CREATING slice reports 0 endpoints; rank ids must not shift
    the READY slice's hosts into its range."""
    from skypilot_tpu.provision.gcp import instance as gcp_instance
    from skypilot_tpu.provision.gcp import tpu_api

    monkeypatch.setenv('SKYT_GCP_TOKEN', 'fake-token')
    sess, _ = _fake_session([
        ('GET', '/nodes/ms-s0', 200, {'state': 'CREATING',
                                      'networkEndpoints': []}),
        ('GET', '/nodes/ms-s1', 200, {
            'state': 'READY',
            'networkEndpoints': [{'ipAddress': '10.0.1.2'},
                                 {'ipAddress': '10.0.1.3'}]}),
    ])
    monkeypatch.setattr(tpu_api, '_session', sess)
    out = gcp_instance.query_instances(
        'ms', {'project': 'p', 'availability_zone': 'z',
               'num_slices': 2, 'hosts_per_slice': 2})
    assert out == {'ms-host-0': 'pending', 'ms-host-1': 'pending',
                   'ms-host-2': 'running', 'ms-host-3': 'running'}
