"""Client-local source translation for VM-hosted controllers.

Covers skypilot_tpu/utils/controller_utils.py (the analog of reference
sky/utils/controller_utils.py:567
`maybe_translate_local_file_mounts_and_sync_up`): after translation a
task must be launchable from a machine that has never seen the client's
filesystem. Uses the `local://` store so no cloud CLI runs.
"""
import os
import subprocess

import pytest

import skypilot_tpu as sky
from skypilot_tpu import state
from skypilot_tpu.data import cloud_stores
from skypilot_tpu.data import data_utils
from skypilot_tpu.utils import controller_utils


@pytest.fixture()
def translate_env(tmp_path, tmp_state_dir, monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_STORAGE_ROOT', str(tmp_path / 'buckets'))
    monkeypatch.setenv('SKYT_DEFAULT_STORE', 'local')
    yield tmp_path


def _translate(task):
    controller_utils.maybe_translate_local_file_mounts_and_sync_up(
        task, task_type='jobs')


def test_workdir_translated_to_bucket(translate_env, tmp_path):
    workdir = tmp_path / 'wd'
    workdir.mkdir()
    (workdir / 'train.py').write_text('print("hi")\n')
    task = sky.Task(name='t', run='python train.py', workdir=str(workdir))
    _translate(task)
    assert task.workdir is None
    spec = task.storage_mounts[controller_utils.WORKDIR_DST]
    assert spec['source'].startswith('local://skyt-workdir-')
    assert spec['mode'] == 'COPY'
    assert spec['persistent'] is False
    # The bucket actually holds the workdir content (uploaded eagerly).
    bucket_dir = os.path.join(data_utils.local_store_root(), spec['name'])
    assert os.path.isfile(os.path.join(bucket_dir, 'train.py'))
    # And the ephemeral bucket is registered for controller cleanup.
    assert state.get_storage(spec['name']) is not None


def test_dir_file_mount_becomes_storage_mount(translate_env, tmp_path):
    src = tmp_path / 'dataset'
    src.mkdir()
    (src / 'x.csv').write_text('1,2\n')
    task = sky.Task(name='t', run='ls', file_mounts={'/data': str(src)})
    _translate(task)
    assert task.file_mounts == {}
    spec = task.storage_mounts['/data']
    assert spec['source'].startswith('local://skyt-fm-')
    bucket_dir = os.path.join(data_utils.local_store_root(), spec['name'])
    assert os.path.isfile(os.path.join(bucket_dir, 'x.csv'))


def test_file_mounts_rewritten_to_bucket_uris(translate_env, tmp_path):
    cfg = tmp_path / 'config.yaml'
    cfg.write_text('lr: 3e-4\n')
    task = sky.Task(name='t', run='cat cfg/config.yaml',
                    file_mounts={'~/cfg/config.yaml': str(cfg),
                                 '/etc2/conf2.yaml': str(cfg)})
    _translate(task)
    # Both dsts point at the SAME staged object (same source file), with
    # the ~/ prefix normalized away (runner cwd is the remote home).
    uris = set(task.file_mounts.values())
    assert len(uris) == 1
    uri = uris.pop()
    assert uri.startswith('local://skyt-fm-files-')
    assert uri.endswith('/file-0')
    assert set(task.file_mounts) == {'cfg/config.yaml', '/etc2/conf2.yaml'}
    # Object content survived the staging hardlink + upload.
    scheme, bucket, path = data_utils.split_uri(uri)
    staged = os.path.join(data_utils.local_store_root(), bucket, path)
    assert open(staged, encoding='utf-8').read() == 'lr: 3e-4\n'


def test_cloud_uri_mounts_untouched(translate_env):
    task = sky.Task(name='t', run='ls',
                    file_mounts={'/d': 'gs://some-bucket/path'})
    _translate(task)
    assert task.file_mounts == {'/d': 'gs://some-bucket/path'}
    assert task.storage_mounts == {}


def test_noop_without_local_sources(translate_env):
    task = sky.Task(name='t', run='echo hi')
    _translate(task)
    assert task.workdir is None
    assert task.file_mounts == {}
    assert task.storage_mounts == {}


def test_existing_storage_mount_local_source_uploaded(
        translate_env, tmp_path):
    src = tmp_path / 'corpus'
    src.mkdir()
    (src / 'a.txt').write_text('aaa\n')
    task = sky.Task(name='t', run='ls /mnt/corpus',
                    storage_mounts={'/mnt/corpus': {
                        'name': 'my-corpus', 'source': str(src),
                        'mode': 'COPY'}})
    _translate(task)
    spec = task.storage_mounts['/mnt/corpus']
    assert spec['source'] == 'local://my-corpus'
    assert spec['persistent'] is True  # user default preserved
    bucket_dir = os.path.join(data_utils.local_store_root(), 'my-corpus')
    assert os.path.isfile(os.path.join(bucket_dir, 'a.txt'))


def test_translated_task_yaml_is_self_contained(translate_env, tmp_path):
    """The serialized task must round-trip with no client paths left."""
    workdir = tmp_path / 'wd'
    workdir.mkdir()
    (workdir / 'm.txt').write_text('m\n')
    task = sky.Task(name='t', run='cat m.txt', workdir=str(workdir))
    _translate(task)
    cfg = task.to_yaml_config()
    assert 'workdir' not in cfg
    assert str(tmp_path) not in str(cfg)
    reloaded = sky.Task.from_yaml_config(cfg)
    assert controller_utils.WORKDIR_DST in reloaded.storage_mounts


def test_download_command_file_vs_dir_dispatch(translate_env, tmp_path):
    """cloud_stores.download_command decides file-vs-prefix at runtime:
    a single object lands AS the target path, a prefix syncs INTO it."""
    root = data_utils.local_store_root()
    os.makedirs(os.path.join(root, 'b', 'sub'), exist_ok=True)
    with open(os.path.join(root, 'b', 'sub', 'f.txt'), 'w',
              encoding='utf-8') as f:
        f.write('content\n')

    file_tgt = tmp_path / 'out' / 'renamed.txt'
    cmd = cloud_stores.download_command('local://b/sub/f.txt',
                                        str(file_tgt))
    subprocess.run(['bash', '-c', cmd], check=True)
    assert file_tgt.read_text() == 'content\n'

    dir_tgt = tmp_path / 'outdir'
    cmd = cloud_stores.download_command('local://b/sub', str(dir_tgt))
    subprocess.run(['bash', '-c', cmd], check=True)
    assert (dir_tgt / 'f.txt').read_text() == 'content\n'


def test_workdir_collision_detected_after_normalization(
        translate_env, tmp_path):
    """`~/skyt_workdir` must collide with the workdir target even though
    the raw strings differ (both normalize to the same remote dir)."""
    wd = tmp_path / 'wd'
    wd.mkdir()
    assets = tmp_path / 'assets'
    assets.mkdir()
    task = sky.Task(name='t', run='ls', workdir=str(wd),
                    file_mounts={'~/skyt_workdir': str(assets)})
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidTaskError, match='skyt_workdir'):
        _translate(task)


def test_storage_mount_requested_store_honored(
        translate_env, tmp_path, monkeypatch):
    """An explicit `store:` in a storage mount wins over the session
    default (a gcs default must not hijack a local-store spec)."""
    monkeypatch.setenv('SKYT_DEFAULT_STORE', 'gcs')
    src = tmp_path / 'd'
    src.mkdir()
    (src / 'f').write_text('x')
    task = sky.Task(name='t', run='ls',
                    storage_mounts={'/m': {'name': 'picky', 'store': 'local',
                                           'source': str(src),
                                           'mode': 'COPY'}})
    _translate(task)
    spec = task.storage_mounts['/m']
    assert spec['source'] == 'local://picky'
    assert spec['store'] == 'local'


def test_validate_before_upload_leaves_no_buckets(translate_env, tmp_path):
    """A bad source anywhere must fail BEFORE any bucket is created."""
    good = tmp_path / 'good'
    good.mkdir()
    task = sky.Task(name='t', run='ls',
                    file_mounts={'/a': str(good),
                                 '/b': str(tmp_path / 'missing')})
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidTaskError, match='missing'):
        _translate(task)
    root = data_utils.local_store_root()
    assert not os.path.isdir(root) or os.listdir(root) == []


def test_s3_download_command_dispatches_on_head_object():
    """The s3 file-vs-prefix dispatch must probe with head-object, not
    infer from `aws s3 cp` failure (which would mask auth errors as an
    empty prefix sync)."""
    cmd = cloud_stores.download_command('s3://bkt/model.pt', '/out/model.pt')
    assert 'head-object' in cmd and '--bucket bkt' in cmd \
        and '--key model.pt' in cmd
    assert 'aws s3 cp' in cmd and 'aws s3 sync' in cmd
    assert '2>/dev/null) ||' not in cmd


def test_cleanup_removes_translated_file_bucket(translate_env, tmp_path):
    """Single-file mounts are rewritten to plain URI strings, not
    dict specs — cleanup must still find and delete their shared
    staging bucket (and leave user-supplied URI mounts alone)."""
    cfg = tmp_path / 'c.yaml'
    cfg.write_text('x: 1\n')
    task = sky.Task(name='t', run='cat c.yaml',
                    file_mounts={'~/c.yaml': str(cfg)})
    _translate(task)
    uri = task.file_mounts['c.yaml']
    _, bucket, _ = data_utils.split_uri(uri)
    assert bucket.startswith('skyt-fm-files-')
    assert state.get_storage(bucket) is not None
    controller_utils.cleanup_ephemeral_storages(task.to_yaml_config())
    assert state.get_storage(bucket) is None
    assert not os.path.isdir(
        os.path.join(data_utils.local_store_root(), bucket))


def test_validate_rejects_missing_workdir(translate_env, tmp_path):
    """A workdir that vanished after Task construction (deleted dir,
    task deserialized from stale state) must fail validation before any
    upload, not blow up mid-translation after earlier tasks' buckets
    are uploaded. (Task.__init__ validates at construction; this guards
    the mutation/staleness window.)"""
    wd = tmp_path / 'wd'
    wd.mkdir()
    task = sky.Task(name='t', run='ls', workdir=str(wd))
    wd.rmdir()
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidTaskError, match='workdir'):
        controller_utils.validate_local_sources(task)


def test_validate_rejects_file_dst_collision(translate_env, tmp_path):
    """`~/cfg.yaml` and `cfg.yaml` collide after normalization: silent
    last-one-wins would drop one of the two files from the replica."""
    a = tmp_path / 'a.yaml'
    a.write_text('a\n')
    b = tmp_path / 'b.yaml'
    b.write_text('b\n')
    task = sky.Task(name='t', run='ls',
                    file_mounts={'~/cfg.yaml': str(a),
                                 'cfg.yaml': str(b)})
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidTaskError, match='collide'):
        _translate(task)


def test_cleanup_ephemeral_storages(translate_env, tmp_path):
    """The serve-side teardown helper removes only non-persistent,
    state-registered buckets."""
    wd = tmp_path / 'wd'
    wd.mkdir()
    (wd / 'f').write_text('x')
    task = sky.Task(name='t', run='ls', workdir=str(wd))
    _translate(task)
    spec = task.storage_mounts[controller_utils.WORKDIR_DST]
    assert state.get_storage(spec['name']) is not None
    controller_utils.cleanup_ephemeral_storages(task.to_yaml_config())
    assert state.get_storage(spec['name']) is None
    bucket_dir = os.path.join(data_utils.local_store_root(), spec['name'])
    assert not os.path.isdir(bucket_dir)
