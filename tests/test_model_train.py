"""Model + sharded-training tests on the virtual 8-device CPU mesh — the
fake multi-host harness the reference lacks (SURVEY.md §4 implication)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


@pytest.fixture(scope='module')
def debug_setup():
    cfg = llama.CONFIGS['debug']
    model = llama.LlamaModel(cfg)
    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(dp=2, fsdp=2, tp=2))
    tcfg = trainer.TrainerConfig(warmup_steps=2, total_steps=10,
                                 learning_rate=1e-2)
    tx = trainer.make_optimizer(tcfg)
    sample = jnp.zeros((8, 32), jnp.int32)
    state, shardings = trainer.create_sharded_state(
        model, tx, mesh, sample, jax.random.PRNGKey(0))
    return cfg, model, mesh, tx, state


def _batch(b=8, s=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, (b, s + 1))
    return {'tokens': jnp.array(toks[:, :-1], jnp.int32),
            'targets': jnp.array(toks[:, 1:], jnp.int32)}


class TestMeshSpec:
    def test_shapes(self):
        spec = mesh_lib.MeshSpec(dp=2, fsdp=2, tp=2)
        assert spec.num_devices == 8
        assert mesh_lib.build_mesh(spec).shape['tp'] == 2

    def test_auto_spec_defaults_to_fsdp(self):
        spec = mesh_lib.auto_spec(8)
        assert spec.fsdp == 8 and spec.num_devices == 8

    def test_auto_spec_model_size(self):
        # 8B params (~134 GiB state) on 16GiB chips: needs fsdp >= 16/tp=4.
        spec = mesh_lib.auto_spec(16, tp=4, model_params_b=8.0,
                                  hbm_gib_per_device=16.0)
        assert spec.num_devices == 16
        assert spec.fsdp * spec.tp >= 8

    def test_topology_mesh(self):
        from skypilot_tpu.accelerators import parse_tpu
        spec = mesh_lib.mesh_for_topology(parse_tpu('tpu-v5e-16'))
        assert spec.num_devices == 16
        assert spec.tp == 4  # chips per host

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            mesh_lib.auto_spec(8, tp=3)


class TestModel:
    def test_param_count_matches_analytic(self, debug_setup):
        cfg, model, mesh, tx, state = debug_setup
        n = sum(x.size for x in jax.tree.leaves(state.params))
        assert n == cfg.num_params()

    def test_params_are_sharded(self, debug_setup):
        cfg, model, mesh, tx, state = debug_setup
        shardings = {jax.tree_util.keystr(k): v.sharding
                     for k, v in jax.tree_util.tree_leaves_with_path(
                         state.params)}
        # At least one param must be sharded over fsdp and one over tp.
        specs = [tuple(s.spec) for s in shardings.values()]
        flat = [ax for spec in specs for ax in spec if ax is not None]
        assert 'fsdp' in str(flat) and 'tp' in str(flat), specs

    def test_loss_decreases(self, debug_setup):
        cfg, model, mesh, tx, state = debug_setup
        # donate=False: the module-scoped fixture state must survive for
        # later tests (donation invalidates the input buffers).
        step = trainer.make_train_step(model, tx, mesh, donate=False)
        batch = _batch()
        losses = []
        for _ in range(5):
            state, m = step(state, batch)
            losses.append(float(m['loss']))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_scan_and_unrolled_agree(self):
        import dataclasses
        cfg = dataclasses.replace(llama.CONFIGS['debug'], scan_layers=True)
        cfg_u = dataclasses.replace(cfg, scan_layers=False)
        tokens = _batch(b=2, s=16)['tokens']
        m_s = llama.LlamaModel(cfg)
        vars_s = m_s.init(jax.random.PRNGKey(1), tokens)
        out_s = m_s.apply(vars_s, tokens)
        # Map scanned params (stacked on axis 0) to unrolled layer params.
        import flax
        p = flax.core.unfreeze(vars_s)['params']
        stacked = p.pop('layers')
        for i in range(cfg.n_layers):
            p[f'layer_{i}'] = jax.tree.map(lambda x: x[i], stacked)
        out_u = llama.LlamaModel(cfg_u).apply({'params': p}, tokens)
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_u),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_head_shapes(self):
        cfg = llama.CONFIGS['debug']
        assert cfg.n_heads % cfg.n_kv_heads == 0

    def test_remat_policies_agree(self):
        """remat_policy changes WHAT the backward recomputes, never the
        math: loss and grads under 'dots' (save matmul outputs) must
        match 'full' (save nothing) — the bench's SKYT_BENCH_REMAT knob
        flips between them."""
        import dataclasses

        import flax

        tokens = _batch(b=2, s=16)
        grads = {}
        for pol in ('full', 'dots'):
            cfg = dataclasses.replace(llama.CONFIGS['debug'],
                                      remat=True, remat_policy=pol)
            model = llama.LlamaModel(cfg)
            variables = model.init(jax.random.PRNGKey(3),
                                   tokens['tokens'])

            def loss_fn(params):
                logits = model.apply({'params': params},
                                     tokens['tokens'])
                loss, _ = trainer.cross_entropy_loss(logits,
                                                     tokens['targets'])
                return loss

            loss, g = jax.value_and_grad(loss_fn)(
                flax.core.unfreeze(variables)['params'])
            grads[pol] = (float(loss), g)
        assert np.isclose(grads['full'][0], grads['dots'][0], rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            grads['full'][1], grads['dots'][1])

    def test_eval_step(self, debug_setup):
        cfg, model, mesh, tx, state = debug_setup
        ev = trainer.make_eval_step(model, mesh)
        m = ev(state.params, _batch())
        assert np.isfinite(float(m['loss']))


class TestOps:
    def test_gqa_matches_repeated_mha(self):
        from skypilot_tpu.ops.attention import mha_reference
        rng = np.random.default_rng(0)
        b, s, hq, hkv, d = 2, 16, 4, 2, 8
        q = jnp.array(rng.normal(size=(b, s, hq, d)), jnp.float32)
        k = jnp.array(rng.normal(size=(b, s, hkv, d)), jnp.float32)
        v = jnp.array(rng.normal(size=(b, s, hkv, d)), jnp.float32)
        out = mha_reference(q, k, v, causal=True)
        # repeat kv to full heads -> plain MHA must agree
        k_full = jnp.repeat(k, hq // hkv, axis=2)
        v_full = jnp.repeat(v, hq // hkv, axis=2)
        out_full = mha_reference(q, k_full, v_full, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_full),
                                   rtol=1e-5, atol=1e-5)

    def test_causality(self):
        from skypilot_tpu.ops.attention import mha_reference
        rng = np.random.default_rng(0)
        b, s, h, d = 1, 8, 2, 4
        q = jnp.array(rng.normal(size=(b, s, h, d)), jnp.float32)
        k = jnp.array(rng.normal(size=(b, s, h, d)), jnp.float32)
        v = jnp.array(rng.normal(size=(b, s, h, d)), jnp.float32)
        out1 = mha_reference(q, k, v, causal=True)
        # Perturbing the future must not change earlier outputs.
        k2 = k.at[:, -1].set(99.0)
        v2 = v.at[:, -1].set(99.0)
        out2 = mha_reference(q, k2, v2, causal=True)
        np.testing.assert_allclose(np.asarray(out1[:, :-1]),
                                   np.asarray(out2[:, :-1]), atol=1e-6)

    def test_segment_isolation(self):
        from skypilot_tpu.ops.attention import mha_reference
        rng = np.random.default_rng(0)
        b, s, h, d = 1, 8, 2, 4
        q = jnp.array(rng.normal(size=(b, s, h, d)), jnp.float32)
        k = jnp.array(rng.normal(size=(b, s, h, d)), jnp.float32)
        v = jnp.array(rng.normal(size=(b, s, h, d)), jnp.float32)
        seg = jnp.array([[0, 0, 0, 0, 1, 1, 1, 1]])
        out = mha_reference(q, k, v, causal=True, segment_ids=seg)
        # second segment must ignore first-segment K/V entirely
        out_iso = mha_reference(q[:, 4:], k[:, 4:], v[:, 4:], causal=True)
        np.testing.assert_allclose(np.asarray(out[:, 4:]),
                                   np.asarray(out_iso), rtol=1e-5, atol=1e-5)

    def test_rope_rotation_preserves_norm(self):
        from skypilot_tpu.ops import rope
        pos = jnp.arange(16)[None]
        cos, sin = rope.rope_freqs(pos, 8, 10000.0)
        x = jnp.ones((1, 16, 2, 8))
        y = rope.apply_rope(x, cos, sin)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(x), axis=-1),
                                   np.linalg.norm(np.asarray(y), axis=-1),
                                   rtol=1e-5)

    def test_rms_norm(self):
        from skypilot_tpu.ops import norms
        x = jnp.array(np.random.default_rng(0).normal(size=(4, 8)) * 10,
                      jnp.float32)
        y = norms.rms_norm(x, jnp.ones((8,)))
        rms = np.sqrt(np.mean(np.asarray(y) ** 2, -1))
        np.testing.assert_allclose(rms, 1.0, rtol=1e-3)


def test_jsonl_batches_with_hf_tokenizer(tmp_path):
    """--data-tokenizer: JSONL 'text' rows tokenize through the real
    tokenizer instead of the byte fallback."""
    import json as _json

    from skypilot_tpu.train import sft

    data = tmp_path / 'd.jsonl'
    data.write_text(_json.dumps({'text': 'hello world'}) + '\n')

    class FakeTok:
        def encode(self, text):
            return [7] * len(text.split())
    got = next(sft.jsonl_batches(str(data), 256, 1, 4,
                                 tokenizer=FakeTok()))
    # stream: 7 7 0 7 7 0 ... packed into [1, 5] -> tokens [1,4]
    assert got['tokens'].tolist() == [[7, 7, 0, 7]]
    byte = next(sft.jsonl_batches(str(data), 256, 1, 4))
    assert byte['tokens'].tolist() == [[104, 101, 108, 108]]  # 'hell'


@pytest.mark.parametrize('family', ['gemma2', 'qwen3', 'phi3'])
def test_family_train_step(family):
    """One train step (forward + backward, remat + scan) through each
    family's special machinery — the gradient of the windowed/
    soft-capped/qk-normed attention has no other coverage. Gemma-2 is
    the hard case: traced layer-index window gating inside a
    rematerialized scan body."""
    import dataclasses

    base = dataclasses.replace(llama.CONFIGS['debug'], remat=True,
                               max_seq_len=64)
    cfg = {
        'gemma2': dataclasses.replace(
            base, n_layers=4, mlp_act='gelu_tanh',
            norm_zero_centered=True, embed_scale=True,
            tie_embeddings=True, head_dim_override=16,
            sliding_window=8, window_pattern=2, attn_softcap=30.0,
            final_softcap=20.0, attn_scale=32.0 ** -0.5,
            sandwich_norms=True),
        'qwen3': dataclasses.replace(base, qk_norm=True,
                                     head_dim_override=32,
                                     tie_embeddings=True),
        'phi3': dataclasses.replace(base, sliding_window=8),
    }[family]
    model = llama.LlamaModel(cfg)
    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(fsdp=2, tp=2, dp=2))
    tcfg = trainer.TrainerConfig(warmup_steps=2, total_steps=100)
    tx = trainer.make_optimizer(tcfg)
    sample = jnp.zeros((2, 32), jnp.int32)
    state, _ = trainer.create_sharded_state(model, tx, mesh, sample,
                                            jax.random.PRNGKey(0))
    step = trainer.make_train_step(model, tx, mesh, donate=False)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (2, 33))
    batch = {'tokens': jnp.asarray(toks[:, :-1], jnp.int32),
             'targets': jnp.asarray(toks[:, 1:], jnp.int32)}
    losses = []
    for _ in range(3):
        state, m = step(state, batch)
        losses.append(float(m['loss']))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]   # same batch: must overfit
