"""Flash kernel (interpret mode), ring attention, and MoE tests on the
virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import moe as moe_lib
from skypilot_tpu.ops.attention import mha_reference
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import ring_attention
from skypilot_tpu.train import trainer

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


def _qkv(b=2, s=64, hq=4, hkv=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.array(rng.normal(size=(b, s, hq, d)), jnp.float32)
    k = jnp.array(rng.normal(size=(b, s, hkv, d)), jnp.float32)
    v = jnp.array(rng.normal(size=(b, s, hkv, d)), jnp.float32)
    return q, k, v


class TestFlashKernel:
    """Interpret-mode equivalence with the XLA reference (the same kernel
    runs compiled on TPU; see bench.py)."""

    @pytest.mark.parametrize('causal', [True, False])
    def test_matches_reference(self, causal):
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(s=256, d=64)
        out_f = flash_attention(q, k, v, causal, None, 128, 128)
        out_r = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r),
                                   rtol=1e-5, atol=1e-5)

    def test_gqa_index_map(self):
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(s=128, hq=8, hkv=2, d=64)
        out_f = flash_attention(q, k, v, True, None, 128, 128)
        out_r = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_flows(self):
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(s=128, d=64)
        g_f = jax.grad(
            lambda q: flash_attention(q, k, v, True, None, 128, 128).sum()
        )(q)
        g_r = jax.grad(
            lambda q: mha_reference(q, k, v, causal=True).sum())(q)
        np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_r),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize('causal', [True, False])
    def test_backward_kernel_dqkv(self, causal):
        """Pallas dq/dkv kernels vs XLA reference grads — a non-trivial
        upstream cotangent exercises delta = rowsum(dO*O)."""
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(s=256, d=64)
        w = jnp.array(np.random.default_rng(3).normal(
            size=(2, 256, 4, 64)), jnp.float32)

        def loss_f(q, k, v):
            return (flash_attention(q, k, v, causal, None,
                                    128, 128) * w).sum()

        def loss_r(q, k, v):
            return (mha_reference(q, k, v, causal=causal) * w).sum()

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, 'q k v'.split()):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f'd{name}')

    def test_backward_kernel_gqa(self):
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(s=128, hq=8, hkv=2, d=64)

        def loss_f(q, k, v):
            return flash_attention(q, k, v, True, None, 128, 128).sum()

        def loss_r(q, k, v):
            return mha_reference(q, k, v, causal=True).sum()

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, 'q k v'.split()):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f'd{name}')

    def test_segment_ids_in_kernel(self):
        """Packed sequences masked in-kernel, forward and backward."""
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv(s=256, d=64)
        seg = np.zeros((2, 256), np.int32)
        seg[:, 100:180] = 1
        seg[:, 180:] = 2
        seg = jnp.asarray(seg)

        out_f = flash_attention(q, k, v, True, seg, 128, 128)
        out_r = mha_reference(q, k, v, causal=True, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r),
                                   rtol=1e-5, atol=1e-5)

        gf = jax.grad(lambda q, k, v: flash_attention(
            q, k, v, True, seg, 128, 128).sum(), argnums=(0, 1, 2))(
                q, k, v)
        gr = jax.grad(lambda q, k, v: mha_reference(
            q, k, v, causal=True, segment_ids=seg).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, 'q k v'.split()):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f'd{name}')


class TestRingAttention:
    def test_matches_reference(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(cp=4, tp=2))
        q, k, v = _qkv()
        out = ring_attention.ring_attention_sharded(q, k, v, mesh)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(cp=8))
        q, k, v = _qkv(s=32)
        g1 = jax.grad(lambda q: ring_attention.ring_attention_sharded(
            q, k, v, mesh).sum())(q)
        g2 = jax.grad(lambda q: mha_reference(q, k, v, causal=True).sum())(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-4)

    def test_flash_ring_matches_einsum_and_reference(self):
        """Flash-eligible shapes (chunk 128, d=64): the flash-forward
        ring must match both the einsum ring and full attention, and its
        grads (routed through the einsum backward) must match too."""
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(cp=4))
        q, k, v = _qkv(b=1, s=512, hq=2, hkv=2, d=64)
        out_flash = ring_attention.ring_attention_sharded(q, k, v, mesh)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out_flash),
                                   np.asarray(ref), rtol=2e-5,
                                   atol=2e-5)
        import os
        os.environ['SKYT_RING_IMPL'] = 'xla'
        try:
            out_einsum = ring_attention.ring_attention_sharded(
                q, k, v, mesh)
        finally:
            del os.environ['SKYT_RING_IMPL']
        np.testing.assert_allclose(np.asarray(out_flash),
                                   np.asarray(out_einsum), rtol=2e-5,
                                   atol=2e-5)
        g1 = jax.grad(lambda q: ring_attention.ring_attention_sharded(
            q, k, v, mesh).astype(jnp.float32).sum())(q)
        g2 = jax.grad(lambda q: mha_reference(
            q, k, v, causal=True).astype(jnp.float32).sum())(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-4)

    def test_model_with_ring_attention(self):
        """cfg.attn_impl='ring' trains end-to-end on a cp mesh."""
        import dataclasses
        from skypilot_tpu.models import llama
        cfg = dataclasses.replace(llama.CONFIGS['debug'], attn_impl='ring')
        model = llama.LlamaModel(cfg)
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(cp=2, fsdp=2, tp=2))
        tx = trainer.make_optimizer(
            trainer.TrainerConfig(warmup_steps=1, total_steps=5))
        sample = jnp.zeros((4, 64), jnp.int32)
        state, _ = trainer.create_sharded_state(model, tx, mesh, sample,
                                                jax.random.PRNGKey(0))
        step = trainer.make_train_step(model, tx, mesh, donate=False)
        rng = np.random.default_rng(0)
        batch = {'tokens': jnp.array(rng.integers(0, 256, (4, 64)),
                                     jnp.int32),
                 'targets': jnp.array(rng.integers(0, 256, (4, 64)),
                                      jnp.int32)}
        state, m = step(state, batch)
        assert np.isfinite(float(m['loss']))


class TestMoE:
    def test_trains_on_ep_mesh(self):
        cfg, mcfg = moe_lib.MIXTRAL_CONFIGS['debug-moe']
        model = moe_lib.MixtralModel(cfg, mcfg)
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(dp=2, ep=2, tp=2))
        tx = trainer.make_optimizer(
            trainer.TrainerConfig(warmup_steps=1, total_steps=10,
                                  learning_rate=1e-2))
        sample = jnp.zeros((8, 32), jnp.int32)
        state, _ = trainer.create_sharded_state(model, tx, mesh, sample,
                                                jax.random.PRNGKey(0))
        step = trainer.make_train_step(model, tx, mesh, donate=False)
        rng = np.random.default_rng(0)
        batch = {'tokens': jnp.array(rng.integers(0, 256, (8, 32)),
                                     jnp.int32),
                 'targets': jnp.array(rng.integers(0, 256, (8, 32)),
                                      jnp.int32)}
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m['loss']))
        assert losses[-1] < losses[0]
        specs = {str(x.sharding.spec) for x in jax.tree.leaves(state.params)}
        assert any('ep' in s for s in specs)

    def test_capacity_drops_overflow(self):
        """With capacity_factor tiny, most tokens are dropped but the layer
        still runs and the output stays finite."""
        import dataclasses
        cfg, mcfg = moe_lib.MIXTRAL_CONFIGS['debug-moe']
        mcfg = dataclasses.replace(mcfg, capacity_factor=0.1)
        layer = moe_lib.MoeMLP(cfg, mcfg)
        x = jnp.ones((2, 32, cfg.dim), jnp.float32)
        vars_ = layer.init(jax.random.PRNGKey(0), x)
        out, aux = layer.apply(vars_, x)
        assert np.isfinite(np.asarray(out)).all()
        assert out.shape == x.shape

    def test_topk_no_capacity_slot_collision(self):
        """Regression: with k=2, a token routed to expert X as 1st choice
        and another routed to X as 2nd choice must land in DIFFERENT
        capacity slots (GShard slot-major positions). Asserts on the
        layer's OWN dispatch tensor (sown intermediate), so reverting the
        moe.py fix fails this test."""
        cfg, mcfg = moe_lib.MIXTRAL_CONFIGS['debug-moe']
        layer = moe_lib.MoeMLP(cfg, mcfg)
        rng = np.random.default_rng(1)
        x = jnp.array(rng.normal(size=(2, 16, cfg.dim)), jnp.float32)
        vars_ = layer.init(jax.random.PRNGKey(0), x)
        (_, _), inter = layer.apply(vars_, x, mutable=['intermediates'])
        dispatch, = inter['intermediates']['dispatch']  # [B,S,E,C]
        # At most one token occupies any (expert, capacity slot).
        occupancy = np.asarray(dispatch.sum(axis=1))    # [B,E,C]
        assert occupancy.max() <= 1.0 + 1e-6, occupancy.max()
        # With the default capacity factor at least one expert receives
        # second-choice traffic in this random batch (the collision case).
        assert dispatch.sum() > 0


class TestWindowedFlash:
    """Sliding-window flash attention (Mistral/Phi-3 prefill): parity
    with the masked XLA reference, forward and backward, including
    windows smaller than a block (the fully-masked-first-block case
    the online-softmax guard exists for)."""

    def _qkv(self, s=256, d=64):
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(2, s, 4, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, s, 2, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, s, 2, d)), jnp.float32)
        return q, k, v

    @pytest.mark.parametrize('window', [7, 64, 100, 256])
    def test_fwd_matches_reference(self, window):
        from skypilot_tpu.ops import attention as attention_ops
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = self._qkv()
        ref = attention_ops.mha_reference(q, k, v, causal=True,
                                          window=window)
        out = flash_attention(q, k, v, True, None, 64, 64,
                              window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_reference(self):
        from skypilot_tpu.ops import attention as attention_ops
        from skypilot_tpu.ops.flash_attention import flash_attention
        q, k, v = self._qkv(s=128)
        w = 48

        gf = jax.grad(lambda q_, k_, v_: (flash_attention(
            q_, k_, v_, True, None, 64, 64, window=w) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q_, k_, v_: (attention_ops.mha_reference(
            q_, k_, v_, causal=True, window=w) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, 'qkv'):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4,
                                       err_msg=f'd{name}')

    def test_dispatch_opt_in(self):
        """attention(): explicit impl='flash' honors a static window
        (it IS the opt-in) and ACTUALLY runs the kernel (interpret
        mode on CPU); a traced window gate is rejected with a message
        naming it."""
        from skypilot_tpu.ops import attention as attention_ops
        q, k, v = self._qkv(s=128)
        ref = attention_ops.mha_reference(q, k, v, causal=True,
                                          window=32)
        out = attention_ops.attention(q, k, v, causal=True, window=32,
                                      impl='flash')
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        with pytest.raises(ValueError, match='window_active'):
            attention_ops.attention(
                q, k, v, causal=True, window=32,
                window_active=jnp.asarray(True), impl='flash')
