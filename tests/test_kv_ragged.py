"""Serving raw-speed stack: int8-quantized KV cache + ragged prefill.

Two golden contracts (docs/performance.md "Serve: raw-speed stack"):

* **int8 KV** may only change arithmetic by bounded quantization
  noise: pool-level insert/gather/append round-trips stay within the
  per-token scale's resolution, the quantized Pallas kernels match the
  dequantizing XLA gather floor, and greedy engine streams track the
  fp engine token-for-token until a near-tie argmax flips (the
  documented bound — random debug weights make near-ties common; the
  test pins first tokens exact plus an aggregate agreement floor).
* **Ragged prefill** may change NOTHING: packed segment-masked
  admission must be byte-identical to the padded batched path AND the
  sequential golden path (greedy, seeded sampling, logprobs), while
  collapsing a mixed-bucket burst into one dispatch with ~0 padded
  positions.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import memory_plan
from skypilot_tpu.infer import paged_cache
from skypilot_tpu.models import llama
from skypilot_tpu.ops import paged_attention

pytestmark = pytest.mark.heavy


@pytest.fixture(scope='module')
def small_model():
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=128)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    return model, params


def _drain(q):
    items = []
    while True:
        it = q.get(timeout=120)
        if it is None:
            return items
        items.append(it)


def _burst(model, params, prompts, sps, **kw):
    """Submit everything before start() (one deterministic same-tick
    burst), drain, return (streams, perf)."""
    eng = engine_lib.InferenceEngine(model, params, num_slots=4,
                                     max_seq_len=128, decode_chunk=4,
                                     cache_mode='paged', page_size=16,
                                     **kw)
    qs = [eng.submit(p, sp)[1] for p, sp in zip(prompts, sps)]
    eng.start()
    try:
        outs = [_drain(q) for q in qs]
    finally:
        eng.stop()
    return outs, dict(eng.perf)


# --------------------------------------------------------- quantization
def test_quantize_kv_roundtrip_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((3, 5, 4, 16)) * 3.0,
                    jnp.float32)
    q, s = paged_cache.quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    deq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
    # Symmetric int8: error per element <= scale/2 = amax/254.
    amax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
    assert np.all(np.abs(deq - np.asarray(x)) <= amax / 254 + 1e-7)
    # All-zero rows stay exactly zero (scale 1.0 guard).
    qz, sz = paged_cache.quantize_kv(jnp.zeros((2, 4)))
    assert np.all(np.asarray(qz) == 0) and np.all(np.asarray(sz) == 1.0)


def _pools(rng, n_layers=2, n_pages=9, h=2, p=16, d=32):
    shape = (n_layers, n_pages, h, p, d)
    fp = {'k': jnp.zeros(shape, jnp.float32)}
    qp = {'k': jnp.zeros(shape, jnp.int8),
          'k_scale': jnp.zeros(shape[:-1], jnp.float32)}
    return fp, qp, (n_layers, h, p, d)


def test_pool_insert_gather_parity():
    """insert_prompt_q + gather_view_layer_q round-trips the prompt KV
    within the quantization bound of the float pool's round-trip."""
    rng = np.random.default_rng(1)
    fp, qp, (l, h, p, d) = _pools(rng)
    kv = jnp.asarray(rng.standard_normal((l, 1, 4 * p, h, d)),
                     jnp.float32)
    ids = jnp.asarray([3, 5, 2, 7], jnp.int32)
    fpool = paged_cache.PagePool.insert_prompt(fp['k'], kv, ids)
    qpool, spool = paged_cache.PagePool.insert_prompt_q(
        qp['k'], qp['k_scale'], kv, ids)
    tables = jnp.asarray([[3, 5, 2, 7, 0, 0]], jnp.int32)
    want = paged_cache.PagePool.gather_view_layer(fpool[0], tables)
    got = paged_cache.PagePool.gather_view_layer_q(
        qpool[0], spool[0], tables, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=float(
                                   np.abs(np.asarray(want)).max()) / 120)


def test_append_token_parity():
    rng = np.random.default_rng(2)
    fp, qp, (l, h, p, d) = _pools(rng)
    tables = jnp.asarray([[1, 2, 0, 0, 0, 0],
                          [4, 0, 0, 0, 0, 0]], jnp.int32)
    lengths = jnp.asarray([p + 3, 5], jnp.int32)
    new_kv = jnp.asarray(rng.standard_normal((2, h, d)), jnp.float32)
    fpool = paged_cache.PagePool.append_token_layer(
        fp['k'][0], new_kv, tables, lengths)
    qpool, spool = paged_cache.PagePool.append_token_layer_q(
        qp['k'][0], qp['k_scale'][0], new_kv, tables, lengths)
    want = paged_cache.PagePool.gather_view_layer(fpool, tables)
    got = paged_cache.PagePool.gather_view_layer_q(qpool, spool,
                                                   tables, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=float(
                                   np.abs(np.asarray(want)).max()) / 120)


def test_append_tokens_parity():
    """Speculative run append (s tokens per slot), quantized vs fp."""
    rng = np.random.default_rng(3)
    fp, qp, (l, h, p, d) = _pools(rng)
    tables = jnp.asarray([[1, 2, 0, 0, 0, 0]], jnp.int32)
    start = jnp.asarray([p - 2], jnp.int32)   # run crosses a page edge
    new_kv = jnp.asarray(rng.standard_normal((1, 4, h, d)), jnp.float32)
    fpool = paged_cache.PagePool.append_tokens_layer(
        fp['k'][0], new_kv, tables, start)
    qpool, spool = paged_cache.PagePool.append_tokens_layer_q(
        qp['k'][0], qp['k_scale'][0], new_kv, tables, start)
    want = paged_cache.PagePool.gather_view_layer(fpool, tables)
    got = paged_cache.PagePool.gather_view_layer_q(qpool, spool,
                                                   tables, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=float(
                                   np.abs(np.asarray(want)).max()) / 120)


# ------------------------------------------------------ kernels (int8)
def _quantized_scene(rng, slots=3, h=2, g=2, p=16, n_pages=13, d=32,
                     mp=4):
    """Random quantized pools + tables/lengths for kernel parity."""
    kq = jnp.asarray(
        rng.integers(-127, 128, (n_pages, h, p, d)), jnp.int8)
    vq = jnp.asarray(
        rng.integers(-127, 128, (n_pages, h, p, d)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.005, 0.03, (n_pages, h, p)),
                     jnp.float32)
    vs = jnp.asarray(rng.uniform(0.005, 0.03, (n_pages, h, p)),
                     jnp.float32)
    tables = jnp.asarray(
        [[1, 2, 3, 0], [4, 5, 0, 0], [6, 7, 8, 9]], jnp.int32)
    lengths = jnp.asarray([p + 4, 3, 3 * p + 1], jnp.int32)
    return kq, vq, ks, vs, tables, lengths


def _ref_attention(q, kq, vq, ks, vs, tables, lengths):
    """Dequantizing-gather + masked reference (the ladder's XLA floor)."""
    from skypilot_tpu.ops import attention as attention_ops
    k_view = paged_cache.PagePool.gather_view_layer_q(
        kq, ks, tables, jnp.float32)
    v_view = paged_cache.PagePool.gather_view_layer_q(
        vq, vs, tables, jnp.float32)
    positions = lengths[:, None] if q.ndim == 3 else \
        lengths[:, None] + jnp.arange(q.shape[1])[None, :]
    qq = q[:, None] if q.ndim == 3 else q
    out = attention_ops.mha_reference(qq, k_view, v_view,
                                      q_positions=positions)
    return out[:, 0] if q.ndim == 3 else out


def test_paged_attention_q_matches_dequant_floor():
    rng = np.random.default_rng(4)
    kq, vq, ks, vs, tables, lengths = _quantized_scene(rng)
    q = jnp.asarray(rng.standard_normal((3, 4, 32)), jnp.float32)
    got = paged_attention.paged_decode_attention_q(
        q, kq, vq, ks, vs, tables, lengths)
    want = _ref_attention(q, kq, vq, ks, vs, tables, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_paged_attention_mq_q_matches_dequant_floor():
    rng = np.random.default_rng(5)
    kq, vq, ks, vs, tables, lengths = _quantized_scene(rng)
    q = jnp.asarray(rng.standard_normal((3, 2, 4, 32)), jnp.float32)
    got = paged_attention.paged_decode_attention_mq_q(
        q, kq, vq, ks, vs, tables, lengths)
    want = _ref_attention(q, kq, vq, ks, vs, tables, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# -------------------------------------------------------- engine (int8)
PROMPTS = [list(range(1, 20)), list(range(5, 55)),
           list(range(7, 40)), list(range(2, 11))]


def test_engine_int8_greedy_parity(small_model):
    """Greedy int8-KV streams vs the fp engine on a fixed prompt set.

    The documented bound (ISSUE 13 acceptance): quantization noise may
    flip an argmax only at a near-tie, so first tokens must be exact
    (prefill runs in float either way) and aggregate agreement must
    stay high; with the fixed seed this is deterministic, not a
    tolerance guess."""
    model, params = small_model
    sps = [engine_lib.SamplingParams(max_new_tokens=8) for _ in PROMPTS]
    fp, _ = _burst(model, params, PROMPTS, sps)
    q8, _ = _burst(model, params, PROMPTS, sps, kv_dtype='int8')
    assert [s[0] for s in q8] == [s[0] for s in fp]
    total = agree = 0
    for a, b in zip(q8, fp):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            total += 1
            agree += int(x == y)
    assert agree / total >= 0.7, (agree, total, q8, fp)
    # Most streams stay token-exact end to end.
    exact = sum(int(a == b) for a, b in zip(q8, fp))
    assert exact >= len(PROMPTS) - 1, (q8, fp)


def test_engine_int8_kernel_matches_xla_floor(small_model, monkeypatch):
    """The quantized Pallas read path and the dequantizing XLA gather
    floor are the same math: token streams must agree."""
    model, params = small_model
    sps = [engine_lib.SamplingParams(max_new_tokens=8) for _ in PROMPTS]
    kernel, _ = _burst(model, params, PROMPTS, sps, kv_dtype='int8')
    monkeypatch.setenv('SKYT_PAGED_ATTN', 'xla')
    floor, _ = _burst(model, params, PROMPTS, sps, kv_dtype='int8')
    assert kernel == floor


def test_engine_int8_spec_decode_matches_plain(small_model):
    """n-gram speculative decoding over the quantized pools (MQ int8
    verify kernel): acceptance gating keeps outputs exactly the plain
    quantized path's."""
    model, params = small_model
    prompt = [1, 2, 3, 1, 2, 3, 1, 2]
    sp = [engine_lib.SamplingParams(max_new_tokens=10)]
    spec, perf = _burst(model, params, [prompt], sp, kv_dtype='int8',
                        spec_decode=3)
    plain, _ = _burst(model, params, [prompt], sp, kv_dtype='int8')
    assert spec == plain
    assert perf['spec_verify_steps'] > 0


def test_engine_int8_prefix_cache_roundtrip(small_model):
    """Prefix sharing over quantized pages: the repeat run reads the
    published int8 pages through the suffix path and must reproduce
    the first run exactly (pages are shared bytes, not recomputed)."""
    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=128, decode_chunk=4,
                                     cache_mode='paged', page_size=16,
                                     kv_dtype='int8')
    eng.start()
    try:
        p = list(range(3, 40))
        sp = engine_lib.SamplingParams(max_new_tokens=6)
        first = eng.generate(p, sp)
        again = eng.generate(p, sp)
    finally:
        eng.stop()
    assert first == again
    assert eng.perf_stats()['prefix_cache']['hit_pages'] > 0


def test_kv_dtype_env_knob(small_model, monkeypatch):
    model, params = small_model
    monkeypatch.setenv('SKYT_KV_DTYPE', 'int8')
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     cache_mode='paged', page_size=16)
    assert eng.kv_quantized and 'k_scale' in eng.cache
    # 'auto' (the default) defers to the env, so a fleet-wide
    # SKYT_KV_DTYPE reaches engines built without the explicit arg.
    eng2 = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=64,
                                      cache_mode='paged', page_size=16,
                                      kv_dtype='auto')
    assert eng2.kv_quantized is True
    monkeypatch.delenv('SKYT_KV_DTYPE')
    eng2b = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='paged', page_size=16)
    assert eng2b.kv_quantized is False
    monkeypatch.setenv('SKYT_KV_DTYPE', 'int8')
    # Dense mode cannot quantize: warn-and-ignore, never a crash.
    eng3 = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=64,
                                      cache_mode='dense')
    assert eng3.kv_dtype == 'auto'
    with pytest.raises(ValueError, match='kv_dtype'):
        engine_lib.InferenceEngine(model, params, num_slots=2,
                                   max_seq_len=64, cache_mode='paged',
                                   kv_dtype='fp8')
    # An env typo must degrade (warn + fp pools), never crash-loop a
    # fleet whose replicas all read the same launch env.
    monkeypatch.setenv('SKYT_KV_DTYPE', 'Int8')
    eng4 = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=64,
                                      cache_mode='paged', page_size=16)
    assert eng4.kv_dtype == 'auto' and 'k_scale' not in eng4.cache


def test_memory_plan_int8_kv():
    """Pages-per-pool at equal HBM: >= 1.9x for every bf16 config
    (d >= 128), and the plan's kv bytes shrink by the same ratio."""
    cfg = llama.CONFIGS['llama3-8b']
    ratio = memory_plan.kv_pages_ratio(cfg, 'int8')
    assert ratio >= 1.9, ratio
    fp = memory_plan.plan_serving(cfg, tp=1, num_slots=8,
                                  max_seq_len=2048)
    q8 = memory_plan.plan_serving(cfg, tp=1, num_slots=8,
                                  max_seq_len=2048, kv_dtype='int8')
    assert q8.kv_pool_bytes < fp.kv_pool_bytes
    got = fp.kv_pool_bytes / q8.kv_pool_bytes
    assert abs(got - ratio) < 0.01, (got, ratio)
    with pytest.raises(ValueError, match='kv_dtype'):
        memory_plan.plan_serving(cfg, tp=1, kv_dtype='fp8')


# ------------------------------------------------------- ragged prefill
MIXED = [list(range(1, 20)), list(range(5, 55)), list(range(7, 40))]


def test_ragged_matches_padded_and_sequential_greedy(small_model):
    model, params = small_model
    sps = [engine_lib.SamplingParams(max_new_tokens=8) for _ in MIXED]
    seq, perf_seq = _burst(model, params, MIXED, sps,
                           batch_admission=False)
    rag, perf_rag = _burst(model, params, MIXED, sps)
    pad, perf_pad = _burst(model, params, MIXED, sps,
                           ragged_prefill=False)
    assert rag == seq
    assert pad == seq
    # The mixed-bucket burst is ONE packed dispatch (the padded path
    # cannot batch across buckets at all: one dispatch per request).
    assert perf_rag['ragged_dispatches'] >= 1
    assert perf_rag['prefill_dispatches'] < perf_seq['prefill_dispatches']
    assert perf_rag['prefill_dispatches'] <= perf_pad['prefill_dispatches']


def test_ragged_matches_sequential_sampled_and_logprobs(small_model):
    model, params = small_model
    sps = [engine_lib.SamplingParams(max_new_tokens=6, temperature=0.9,
                                     top_k=8, top_p=0.95, seed=s,
                                     logprobs=True)
           for s in (11, 22, 33)]
    seq, _ = _burst(model, params, MIXED, sps, batch_admission=False)
    rag, perf = _burst(model, params, MIXED, sps)
    assert perf['ragged_dispatches'] >= 1
    for g, w in zip(rag, seq):
        assert [t for t, _ in g] == [t for t, _ in w]
        np.testing.assert_allclose([lp for _, lp in g],
                                   [lp for _, lp in w],
                                   rtol=1e-5, atol=1e-6)


def test_ragged_padded_fraction(small_model):
    """Page-aligned mixed burst: the packed dispatch computes ~zero
    padded positions while the padded path burns > 40% on pow2
    padding."""
    model, params = small_model
    prompts = [list(range(1, 33)), list(range(2, 66)),
               list(range(3, 19))]      # 32 + 64 + 16 = 112 tokens
    sps = [engine_lib.SamplingParams(max_new_tokens=4)
           for _ in prompts]
    rag, perf_rag = _burst(model, params, prompts, sps)
    _, perf_pad = _burst(model, params, prompts, sps,
                         ragged_prefill=False)
    frac_rag = perf_rag['prefill_padded_tokens'] / \
        perf_rag['prefill_dispatch_tokens']
    frac_pad = perf_pad['prefill_padded_tokens'] / \
        perf_pad['prefill_dispatch_tokens']
    assert frac_rag <= 0.05, (frac_rag, perf_rag)
    assert frac_pad >= 0.4, (frac_pad, perf_pad)


def test_ragged_int8_matches_sequential_int8(small_model):
    """The two tentpole legs compose: packed admission into quantized
    pools equals the sequential quantized path byte-for-byte."""
    model, params = small_model
    sps = [engine_lib.SamplingParams(max_new_tokens=6) for _ in MIXED]
    seq, _ = _burst(model, params, MIXED, sps, batch_admission=False,
                    kv_dtype='int8')
    rag, perf = _burst(model, params, MIXED, sps, kv_dtype='int8')
    assert perf['ragged_dispatches'] >= 1
    assert rag == seq


def test_ragged_prefix_hit_falls_through(small_model):
    """A burst whose head prompt hits the prefix cache must leave the
    packed path (shared pages are cheaper than recompute) and still
    produce identical streams via the sequential suffix path."""
    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=4,
                                     max_seq_len=128, decode_chunk=4,
                                     cache_mode='paged', page_size=16)
    eng.start()
    try:
        p0 = list(range(3, 40))
        sp = engine_lib.SamplingParams(max_new_tokens=6)
        first = eng.generate(p0, sp)
        qs = [eng.submit(p, engine_lib.SamplingParams(max_new_tokens=6))[1]
              for p in (p0, list(range(50, 70)))]
        outs = [_drain(q) for q in qs]
    finally:
        eng.stop()
    assert outs[0] == first
    assert eng.perf_stats()['prefix_cache']['hit_pages'] > 0


def test_ragged_cancel_before_admission(small_model):
    """A request cancelled while waiting inside a ragged batch's FIFO
    prefix gets its terminal None and costs no slot."""
    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=4,
                                     max_seq_len=128, decode_chunk=4,
                                     cache_mode='paged', page_size=16)
    rid0, q0 = eng.submit(MIXED[0],
                          engine_lib.SamplingParams(max_new_tokens=6))
    rid1, q1 = eng.submit(MIXED[1],
                          engine_lib.SamplingParams(max_new_tokens=6))
    assert eng.cancel(rid0)
    eng.start()
    try:
        assert _drain(q0) == []
        assert len(_drain(q1)) == 6
    finally:
        eng.stop()
    assert eng.request_trace(rid0)['status'] == 'cancelled'
