"""Paged-cache engine integration: outputs must match the dense engine
token-for-token, more requests must fit at equal HBM, and pool
exhaustion must defer (not drop or corrupt) admissions."""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.models import llama

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


def _model_and_params(scan_layers=True):
    cfg = dataclasses.replace(llama.CONFIGS['debug'],
                              scan_layers=scan_layers)
    model = llama.LlamaModel(cfg)
    sample = jnp.zeros((1, 8), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), sample)
    return model, params


def _run(engine, prompts, max_new=8):
    engine.start()
    try:
        pairs = [engine.submit(p, engine_lib.SamplingParams(
            max_new_tokens=max_new)) for p in prompts]
        outs = []
        for _, q in pairs:
            toks = []
            while True:
                t = q.get(timeout=300)
                if t is None:
                    break
                toks.append(t)
            outs.append(toks)
        return outs
    finally:
        engine.stop()


def _prompts(vocab, lens, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, n).tolist() for n in lens]


@pytest.mark.parametrize('scan_layers', [True, False])
def test_paged_matches_dense(scan_layers):
    model, params = _model_and_params(scan_layers)
    vocab = model.cfg.vocab_size
    prompts = _prompts(vocab, [5, 17, 33, 9])
    dense = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='dense')
    paged = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='paged', page_size=16)
    out_d = _run(dense, prompts)
    out_p = _run(paged, prompts)
    assert out_d == out_p
    assert all(len(o) == 8 for o in out_p)


def test_paged_holds_more_requests_at_equal_hbm():
    """Pool sized to the DENSE equivalent of 2 slots serves 4 concurrent
    requests (2x request depth at equal cache HBM) because reservations
    track prompt+max_new, not max_seq."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    max_seq, p = 64, 16
    paged = engine_lib.InferenceEngine(
        model, params, num_slots=4, max_seq_len=max_seq,
        cache_mode='paged', page_size=p,
        pool_tokens=2 * max_seq)   # = dense 2-slot cache HBM
    # 4 requests x (prompt 17 + 8 new = 25 tokens -> 2 pages = 32
    # tokens) = 128 tokens = the whole pool: all four fit concurrently.
    prompts = _prompts(vocab, [17, 17, 17, 17])
    outs = _run(paged, prompts)
    assert all(len(o) == 8 for o in outs)
    # And the pool really was capped at the dense-2-slot budget.
    assert (paged.pool.cfg.n_pages - 1) * p == 2 * max_seq

    # Reference: dense engine (4 slots, plenty of HBM) same outputs.
    dense = engine_lib.InferenceEngine(model, params, num_slots=4,
                                       max_seq_len=max_seq,
                                       cache_mode='dense')
    assert _run(dense, prompts) == outs


def test_pool_exhaustion_defers_not_drops():
    """A pool that fits only one request at a time still completes a
    burst of three, in order, with correct outputs."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    paged = engine_lib.InferenceEngine(
        model, params, num_slots=2, max_seq_len=64,
        cache_mode='paged', page_size=16,
        pool_tokens=32)   # 2 pages: one 17+8 request at a time
    prompts = _prompts(vocab, [17, 17, 17])
    outs = _run(paged, prompts)
    assert all(len(o) == 8 for o in outs)
    dense = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='dense')
    assert _run(dense, prompts) == outs
    # All pages returned to the free list after the burst.
    assert paged.pool.free_pages() == paged.pool.cfg.n_pages - 1


def test_slot_reuse_no_corruption():
    """Sequential waves re-admit into released slots/pages; later waves
    must not see earlier waves' KV."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    paged = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='paged', page_size=16)
    dense = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='dense')
    w1 = _prompts(vocab, [9, 21], seed=1)
    w2 = _prompts(vocab, [33, 5], seed=2)
    paged.start()
    dense.start()
    try:
        for wave in (w1, w2):
            p_out = [q for _, q in
                     [paged.submit(x, engine_lib.SamplingParams(
                         max_new_tokens=6)) for x in wave]]
            d_out = [q for _, q in
                     [dense.submit(x, engine_lib.SamplingParams(
                         max_new_tokens=6)) for x in wave]]

            def drain(qs):
                res = []
                for q in qs:
                    toks = []
                    while True:
                        t = q.get(timeout=300)
                        if t is None:
                            break
                        toks.append(t)
                    res.append(toks)
                return res
            assert drain(p_out) == drain(d_out)
    finally:
        paged.stop()
        dense.stop()


def test_moe_paged_matches_dense():
    """The MoE model shares LlamaAttention, so paged decode works for
    Mixtral-style serving too (reference analog: llm/mixtral/serve.yaml
    via vLLM's paged attention)."""
    import dataclasses as _dc

    from skypilot_tpu.models import moe

    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    cfg = _dc.replace(cfg, max_seq_len=64)
    moe_cfg = _dc.replace(moe_cfg, capacity_factor=8.0)
    model = moe.MixtralModel(cfg, moe_cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    prompts = _prompts(cfg.vocab_size, [5, 19, 33])
    dense = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='dense')
    paged = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=64,
                                       cache_mode='paged', page_size=16)
    assert _run(dense, prompts, max_new=6) == _run(paged, prompts,
                                                   max_new=6)


def test_prefix_cache_matches_dense():
    """Requests sharing a long system-prompt prefix: the paged engine
    with prefix caching must produce dense-engine outputs token-for-
    token while actually hitting the prefix cache (vLLM automatic
    prefix caching analog, llm/vllm/serve.yaml)."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    rng = np.random.default_rng(7)
    system = rng.integers(1, vocab, 40).tolist()   # 2.5 pages of 16
    prompts = [system + rng.integers(1, vocab, k).tolist()
               for k in (3, 9, 5)]
    prompts.append(list(prompts[0]))               # exact repeat
    dense = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       cache_mode='dense')
    paged = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       cache_mode='paged', page_size=16)
    assert paged.prefix_caching
    out_d = _run(dense, prompts)
    out_p = _run(paged, prompts)
    assert out_d == out_p
    # Later requests really shared the system prefix's full pages.
    assert paged.pool.prefix_stats['hit_pages'] >= 2


def test_prefix_cache_sequential_repeat():
    """The same prompt served twice: the second admission reuses every
    full page except the last-token page and still matches."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompt = _prompts(vocab, [50], seed=3)[0]
    paged = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=128,
                                       cache_mode='paged', page_size=16)
    out1 = _run(paged, [prompt])
    hits0 = paged.pool.prefix_stats['hit_pages']
    out2 = _run(paged, [prompt])
    assert out1 == out2
    # 50 tokens / 16 = 3 full pages; lookup capped at (50-1)//16 = 3.
    assert paged.pool.prefix_stats['hit_pages'] - hits0 == 3


def test_prefix_caching_off():
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompt = _prompts(vocab, [40], seed=4)[0]
    paged = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=128,
                                       cache_mode='paged', page_size=16,
                                       prefix_caching=False)
    _run(paged, [prompt])
    out = _run(paged, [prompt])
    assert paged.pool.prefix_stats['hit_pages'] == 0
    dense = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=128,
                                       cache_mode='dense')
    assert _run(dense, [prompt]) == out


def test_prefix_cache_suffix_bucket_overflow_falls_back():
    """A cached prefix whose suffix bucket would spill past the per-slot
    view must fall back to a full prefill (not corrupt the cache):
    max_seq 64, pages of 16 -> view span 64; prompt 50 with 16 cached
    leaves a 34-token suffix that buckets to 64 -> 16+64 > 64."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    rng = np.random.default_rng(11)
    head = rng.integers(1, vocab, 16).tolist()
    p_a = head + rng.integers(1, vocab, 34).tolist()
    p_b = head + rng.integers(1, vocab, 34).tolist()
    paged = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=64,
                                       prefill_buckets=[32],
                                       cache_mode='paged', page_size=16)
    dense = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=64,
                                       prefill_buckets=[32],
                                       cache_mode='dense')
    assert _run(paged, [p_a, p_b], max_new=6) == \
        _run(dense, [p_a, p_b], max_new=6)


@pytest.mark.parametrize('prefix_caching', [True, False])
def test_chunked_prefill_matches(prefix_caching):
    """Chunked prefill (vLLM analog): a long prompt prefilled in
    page-aligned chunks interleaved with the engine loop must produce
    EXACTLY the non-chunked engine's outputs, long and short requests
    alike."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    rng = np.random.default_rng(13)
    long_p = rng.integers(1, vocab, 100).tolist()
    prompts = [long_p, rng.integers(1, vocab, 9).tolist(),
               rng.integers(1, vocab, 70).tolist()]
    plain = engine_lib.InferenceEngine(
        model, params, num_slots=2, max_seq_len=256,
        cache_mode='paged', page_size=16,
        prefix_caching=prefix_caching)
    chunked = engine_lib.InferenceEngine(
        model, params, num_slots=2, max_seq_len=256,
        cache_mode='paged', page_size=16,
        prefix_caching=prefix_caching, prefill_chunk=32)
    out_p = _run(plain, prompts, max_new=8)
    out_c = _run(chunked, prompts, max_new=8)
    assert out_p == out_c
    # The long prompts really went through the chunked path.
    assert chunked.perf['prefill_chunks'] >= 100 // 32 + 70 // 32


def test_chunked_prefill_with_prefix_reuse():
    """A chunked admission sharing a published prefix starts its chunks
    AFTER the cached span and still matches."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    rng = np.random.default_rng(17)
    base = rng.integers(1, vocab, 96).tolist()
    variants = [base + rng.integers(1, vocab, k).tolist()
                for k in (5, 40)]
    plain = engine_lib.InferenceEngine(
        model, params, num_slots=1, max_seq_len=256,
        cache_mode='paged', page_size=16)
    chunked = engine_lib.InferenceEngine(
        model, params, num_slots=1, max_seq_len=256,
        cache_mode='paged', page_size=16, prefill_chunk=32)
    assert _run(plain, variants, max_new=6) == \
        _run(chunked, variants, max_new=6)
    assert chunked.pool.prefix_stats['hit_pages'] > 0


def test_bucket_smaller_than_page():
    """Prompt bucket (32) smaller than a page (64): the insert pads the
    prefill KV up to the page span. Regression: the pad length was read
    off the wrong pool axis after the page-major relayout, crashing
    every admission at the server's default page size."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompts = _prompts(vocab, [5, 9])
    paged = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       prefill_buckets=[32],
                                       cache_mode='paged', page_size=64)
    dense = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       prefill_buckets=[32],
                                       cache_mode='dense')
    assert _run(paged, prompts, max_new=4) == _run(dense, prompts,
                                                   max_new=4)


def test_chunked_prefill_delivers_logprobs():
    """The chunked-prefill admission tail must deliver the first
    token's logprob like the plain admission path (regression: the
    first_lp wiring initially missed this site and killed the loop)."""
    import dataclasses

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=256)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    eng = engine_lib.InferenceEngine(
        model, params, num_slots=2, max_seq_len=256,
        cache_mode='paged', page_size=16, prefill_chunk=32)
    eng.start()
    try:
        prompt = list(np.random.default_rng(0).integers(
            1, cfg.vocab_size, 80))   # > prefill_chunk -> chunked path
        _, q = eng.submit([int(t) for t in prompt],
                          engine_lib.SamplingParams(max_new_tokens=4,
                                                    logprobs=True))
        got = []
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            got.append(item)
    finally:
        eng.stop()
    assert len(got) == 4
    assert all(isinstance(t, tuple) and t[1] <= 0.0 for t in got)
