"""Weight-only int8 quantization: tree transform round-trip, quantized
model logits close to float, and the quantized serving path end-to-end.

Reference analog: vLLM quantization flags (llm/vllm/serve.yaml serves
through vLLM, which supplies w8a16); here it is a first-class model
transform (models/quant.py + QuantDense).
"""
import dataclasses
import pytest

import numpy as np

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama, quant
from skypilot_tpu.utils import jax_compat

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


def _float_model(**over):
    cfg = dataclasses.replace(llama.CONFIGS['debug'], **over)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(3, 16, 8)), jnp.float32)  # stacked
    qd = quant._quantize_kernel(w)
    assert qd['kernel'].dtype == jnp.int8
    assert qd['scale'].shape == (3, 8)
    back = quant.dequantize_kernel(qd['kernel'], qd['scale'])
    # Symmetric per-channel: error <= scale/2 per element.
    err = np.abs(np.asarray(back - w))
    bound = np.asarray(qd['scale'])[:, None, :] / 2 + 1e-7
    assert (err <= bound).all()


def test_quantized_tree_matches_quant_model_structure():
    """quantize_params(float tree) must equal the quant='int8' model's
    own init structure/dtypes — the property that makes sharding-spec
    derivation and apply() work unchanged."""
    cfg, model, params = _float_model()
    qparams = quant.quantize_params(params)
    qcfg = dataclasses.replace(cfg, quant='int8')
    qinit = jax.jit(llama.LlamaModel(qcfg).init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    a = jax.tree.structure(qparams)
    b = jax.tree.structure(qinit)
    assert a == b, (a, b)
    import flax.linen as nn
    flat_a = jax_compat.tree_leaves_with_path(
        qparams, is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata))
    flat_b = jax_compat.tree_leaves_with_path(
        qinit, is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata))
    for (pa, x), (pb, y) in zip(flat_a, flat_b):
        assert pa == pb
        val_x = x.unbox() if isinstance(x, nn.meta.AxisMetadata) else x
        val_y = y.unbox() if isinstance(y, nn.meta.AxisMetadata) else y
        assert val_x.dtype == val_y.dtype, (pa, val_x.dtype, val_y.dtype)
        assert val_x.shape == val_y.shape, (pa, val_x.shape, val_y.shape)
        if isinstance(x, nn.meta.AxisMetadata):
            # Logical axis names drive sharding; they must agree too
            # (regression: scan-stacked scales once dropped 'layers').
            assert tuple(x.names) == tuple(y.names), (pa, x.names,
                                                      y.names)


def test_quantized_logits_close():
    cfg, model, params = _float_model()
    qparams = quant.quantize_params(params)
    qmodel = llama.LlamaModel(dataclasses.replace(cfg, quant='int8'))
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(1, cfg.vocab_size, (2, 16)),
        jnp.int32)
    lf = model.apply(params, tokens)
    lq = qmodel.apply(qparams, tokens)
    # int8 per-channel keeps logits within ~1% relative magnitude.
    denom = np.maximum(np.abs(np.asarray(lf)).max(), 1e-6)
    rel = np.abs(np.asarray(lq) - np.asarray(lf)).max() / denom
    assert rel < 0.05, rel
    # And the argmax (greedy token) agrees at nearly every position.
    agree = (np.asarray(lf.argmax(-1)) == np.asarray(lq.argmax(-1)))
    assert agree.mean() > 0.9, agree.mean()


def test_quantized_engine_serves():
    """build_engine(--quantize int8): paged engine prefill+decode works
    and the cache/infra paths are dtype-agnostic."""
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib

    eng = server_lib.build_engine('debug', num_slots=2, max_seq_len=64,
                                  cache_mode='paged',
                                  quantize='int8')
    assert eng.cfg.quant == 'int8'
    eng.start()
    try:
        out = eng.generate([1, 2, 3, 4, 5, 6, 7, 8],
                           engine_lib.SamplingParams(max_new_tokens=6))
        assert len(out) == 6
        assert all(0 <= t < eng.cfg.vocab_size for t in out)
    finally:
        eng.stop()


def test_quantized_engine_tp_sharded():
    """--quantize with --tp 2: the int8 kernels + scales shard over the
    mesh (8-device CPU harness) and decode matches the tp=1 quantized
    engine token-for-token."""
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib

    prompt = [1, 2, 3, 4, 5, 6, 7, 8]

    def run(tp):
        eng = server_lib.build_engine('debug', num_slots=2,
                                      max_seq_len=64, tp=tp,
                                      cache_mode='paged',
                                      quantize='int8')
        eng.start()
        try:
            return eng.generate(
                prompt, engine_lib.SamplingParams(max_new_tokens=6))
        finally:
            eng.stop()

    assert run(2) == run(1)


def test_quantized_moe_structure_and_logits():
    """MoE expert weights quantize too (per-(expert, out-channel)
    scales; router stays float) — tree matches the quant model's init
    and logits stay close."""
    from skypilot_tpu.models import moe

    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    moe_cfg = dataclasses.replace(moe_cfg, capacity_factor=8.0)
    model = moe.MixtralModel(cfg, moe_cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    qparams = quant.quantize_params(params)
    qcfg = dataclasses.replace(cfg, quant='int8')
    qmodel = moe.MixtralModel(qcfg, moe_cfg)
    qinit = jax.jit(qmodel.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    assert jax.tree.structure(qparams) == jax.tree.structure(qinit)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(1, cfg.vocab_size, (2, 16)),
        jnp.int32)
    lf = model.apply(params, tokens)
    lq = qmodel.apply(qparams, tokens)
    denom = np.maximum(np.abs(np.asarray(lf)).max(), 1e-6)
    # Per-token max relative error. A global max-over-tokens bound is
    # NOT meaningful for MoE: routing is a discrete jax.lax.top_k over
    # router scores, and int8 weight noise upstream can flip a
    # near-tie pick — that token then computes through a DIFFERENT
    # expert and its logits legitimately diverge (observed: 1/32
    # tokens at ~36% while the mean sits at ~0.6%). Assert instead
    # that the aggregate error is small and routing flips stay rare —
    # which is what int8 quantization actually promises for MoE.
    tok_rel = np.abs(np.asarray(lq) - np.asarray(lf)).max(-1) / denom
    # Median, not mean: one flipped token would dominate a mean.
    assert np.median(tok_rel) < 0.03, np.median(tok_rel)
    flipped = (tok_rel > 0.05).mean()
    assert flipped <= 0.125, \
        f'{flipped:.2%} of tokens diverged >5% — more than routing-' \
        f'flip noise can explain'


def test_quantized_moe_engine_serves():
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib

    eng = server_lib.build_engine('debug-moe', num_slots=1,
                                  max_seq_len=64, cache_mode='paged',
                                  quantize='int8')
    eng.start()
    try:
        out = eng.generate([1, 2, 3, 4, 5],
                           engine_lib.SamplingParams(max_new_tokens=4))
        assert len(out) == 4
    finally:
        eng.stop()


def test_fused_init_quantize_matches_sequential():
    """build_engine's fused init+quantize (one jit, so the full bf16
    tree is never resident — what lets 8B int8 init on a 16GB chip)
    must produce the same tree as init-then-quantize, modulo fusion
    reordering noise in the scales (±1 quantization step on q)."""
    import numpy as np

    model = llama.LlamaModel(llama.CONFIGS['debug'])
    sample = jnp.zeros((1, 8), jnp.int32)
    seq = quant.quantize_params(
        jax.jit(model.init)(jax.random.PRNGKey(0), sample))
    fused = jax.jit(lambda k: quant.quantize_params(
        model.init(k, sample)))(jax.random.PRNGKey(0))
    la = jax_compat.tree_leaves_with_path(seq)
    lb = jax_compat.tree_leaves_with_path(fused)
    assert len(la) == len(lb)
    for (pa, a), (pb, b) in zip(la, lb):
        assert pa == pb and a.dtype == b.dtype and a.shape == b.shape
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype == np.int8:
            assert np.abs(a.astype(np.int32) -
                          b.astype(np.int32)).max() <= 1
        else:
            np.testing.assert_allclose(a.astype(np.float32),
                                       b.astype(np.float32),
                                       rtol=1e-5, atol=1e-8)


# -------------------------------------------------------------- int4
# w4a16 goes beyond the reference's serving stack: vLLM needs a
# pre-quantized AWQ/GPTQ checkpoint, here any float checkpoint (or
# init) stream-quantizes to int4 group-128 at load.

def test_int4_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(3, 256, 8)), jnp.float32)
    qd = quant._quantize_kernel_int4(w)
    assert qd['kernel'].dtype == jnp.int4
    assert qd['scale'].shape == (3, 2, 8)  # 256 / G=128 -> 2 groups
    back = quant.dequantize_kernel_int4(qd['kernel'], qd['scale'])
    err = np.abs(np.asarray(back - w))
    bound = np.repeat(np.asarray(qd['scale']), 128, axis=-2) / 2 + 1e-7
    assert (err <= bound).all()


def test_int4_dense_matches_dequantized_matmul():
    """QuantDense4's grouped contraction == x @ dequantize(kernel) —
    the scale is constant within a group, so factoring it out of the
    per-group matmul is exact (up to float assoc., tested tight)."""
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(256, 64)), jnp.float32)
    qd = quant._quantize_kernel_int4(w)
    x = jnp.asarray(rng.normal(size=(4, 256)), jnp.float32)

    mod = llama.QuantDense4(features=64, logical_axes=('embed', 'mlp'),
                            dtype=jnp.float32)
    variables = {'params': {'kernel': qd['kernel'],
                            'scale': qd['scale']}}
    got = np.asarray(mod.apply(variables, x))
    want = np.asarray(
        x @ quant.dequantize_kernel_int4(qd['kernel'], qd['scale']))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # bf16 serving dtype: partials accumulate in f32
    # (preferred_element_type), so the only extra error vs the f32
    # reference is the bf16 inputs + one final rounding — NOT a
    # sqrt(n_groups) accumulation drift.
    mod16 = llama.QuantDense4(features=64,
                              logical_axes=('embed', 'mlp'),
                              dtype=jnp.bfloat16)
    got16 = np.asarray(mod16.apply(variables,
                                   x.astype(jnp.bfloat16)),
                       dtype=np.float32)
    # atol scales with output magnitude: bf16 inputs carry 2^-8
    # relative error, outputs here are O(30).
    np.testing.assert_allclose(got16, want, rtol=3e-2,
                               atol=0.02 * np.abs(want).max())


def test_int4_logits_close_and_tree_matches_model():
    cfg, model, params = _float_model()
    qparams = quant.quantize_params(params, mode='int4')
    qcfg = dataclasses.replace(cfg, quant='int4')
    qmodel = llama.LlamaModel(qcfg)
    # Tree structure == what a quant='int4' model initializes.
    import flax.linen as nn
    init_shapes = jax.eval_shape(qmodel.init, jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    flat_a = sorted(str(p) for p, _ in
                    jax.tree_util.tree_leaves_with_path(
                        nn.meta.unbox(init_shapes['params'])))
    flat_b = sorted(str(p) for p, _ in
                    jax.tree_util.tree_leaves_with_path(
                        nn.meta.unbox(qparams['params'])))
    assert flat_a == flat_b
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(1, cfg.vocab_size, (2, 16)),
        jnp.int32)
    lf = model.apply(params, tokens)
    lq = qmodel.apply(qparams, tokens)
    # Exactness claim: the int4 model == the FLOAT model on the
    # dequantized weights (the compute path adds no error beyond the
    # quantization itself). Quality-vs-float is workload-dependent and
    # not asserted tightly on random debug weights — just sanity.
    unboxed = nn.meta.unbox(qparams['params'])

    def dequant(node):
        if isinstance(node, dict) and 'kernel' in node and \
                'scale' in node:
            out = {k: v for k, v in node.items()
                   if k not in ('kernel', 'scale')}
            out['kernel'] = quant.dequantize_kernel_int4(
                node['kernel'], node['scale'])
            return out
        if isinstance(node, dict):
            return {k: dequant(v) for k, v in node.items()}
        return node
    ldq = model.apply({'params': dequant(unboxed)}, tokens)
    np.testing.assert_allclose(np.asarray(lq), np.asarray(ldq),
                               rtol=2e-4, atol=2e-4)
    denom = np.maximum(np.abs(np.asarray(lf)).max(), 1e-6)
    rel = np.abs(np.asarray(lq) - np.asarray(lf)).max() / denom
    assert rel < 0.6, rel  # sanity only (see above)


def test_int4_engine_serves():
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib

    eng = server_lib.build_engine('debug', num_slots=2, max_seq_len=64,
                                  cache_mode='paged',
                                  quantize='int4')
    assert eng.cfg.quant == 'int4'
    eng.start()
    try:
        out = eng.generate([1, 2, 3, 4, 5, 6, 7, 8],
                           engine_lib.SamplingParams(max_new_tokens=6))
        assert len(out) == 6
        assert all(0 <= t < eng.cfg.vocab_size for t in out)
    finally:
        eng.stop()


def test_int4_stream_load_matches_post_quantize(tmp_path):
    """Host-side int4 stream quantizer == on-device quantize_params
    (same grouping, same ±7 symmetric scheme)."""
    from skypilot_tpu.models import weights

    cfg, model, params = _float_model(max_seq_len=64)
    weights.save_hf_checkpoint(cfg, params, str(tmp_path))
    want = quant.quantize_params(
        weights.load_llama_params(cfg, str(tmp_path)), mode='int4')
    got = weights.load_llama_params(cfg, str(tmp_path), quantize='int4')
    la = jax.tree_util.tree_leaves_with_path(want)
    lb = jax.tree_util.tree_leaves_with_path(got)
    assert [str(p) for p, _ in la] == [str(p) for p, _ in lb]
    n_int4 = 0
    for (path, a), (_, b) in zip(la, lb):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, path
        if a.dtype.name == 'int4':
            n_int4 += 1
            assert np.abs(a.astype(np.int32) -
                          b.astype(np.int32)).max() <= 1, path
        else:
            np.testing.assert_allclose(a.astype(np.float32),
                                       b.astype(np.float32),
                                       rtol=1e-5, atol=1e-8,
                                       err_msg=str(path))
    assert n_int4 == 8  # 7 stacked projections + lm_head


def test_int4_rejects_moe():
    from skypilot_tpu.models import moe
    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    model = moe.MixtralModel(cfg, moe_cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(NotImplementedError, match='int4'):
        quant.quantize_params(params, mode='int4')


def test_int4_mixtral_checkpoint_friendly_error(tmp_path):
    """A Mixtral checkpoint with --quantize int4 must say 'int4 is
    llama-family only', not 'unknown quantize mode'."""
    from skypilot_tpu.models import moe, weights
    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    with pytest.raises(NotImplementedError, match='llama-family only'):
        weights.load_mixtral_params(cfg, moe_cfg, str(tmp_path),
                                    quantize='int4')
