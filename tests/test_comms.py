"""Comms plane (docs/observability.md "Comms plane"): link-profile
probe + cache discipline, HLO communication census with mesh-axis
attribution, census × profile estimates, the measurement-driven
placement advisor, and the /fleet/comms route contract."""
import json
import os
import types

import numpy as np
import pytest

from skypilot_tpu.parallel import comms_census
from skypilot_tpu.parallel import comms_profile
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib


@pytest.fixture()
def comms_cache(tmp_path, monkeypatch):
    path = str(tmp_path / 'comms_profile.json')
    monkeypatch.setenv('SKYT_COMMS_CACHE', path)
    comms_profile.reset_for_tests()
    yield path
    comms_profile.reset_for_tests()


class ScriptedClock:
    """Deterministic monotonic clock: advances a fixed dt per call."""

    def __init__(self, dt: float = 0.001, t: float = 100.0) -> None:
        self.t, self.dt = t, dt

    def __call__(self) -> float:
        self.t += self.dt
        return self.t


class FakeDev:
    def __init__(self, i, slice_index=None):
        self.id = i
        self.device_kind = 'fake'
        if slice_index is not None:
            self.slice_index = slice_index

    def __repr__(self):
        return f'FakeDev({self.id})'


def fake_mesh(shape, axis_names, slice_of=None):
    n = int(np.prod(shape))
    devs = [FakeDev(i, slice_of(i) if slice_of else None)
            for i in range(n)]
    return types.SimpleNamespace(
        devices=np.array(devs, dtype=object).reshape(shape),
        axis_names=tuple(axis_names),
        shape=dict(zip(axis_names, shape)))


# ------------------------------------------------------ cache matrix
class TestProfileCache:
    def test_roundtrip_and_process_restart(self, comms_cache):
        cache = comms_profile.get_cache()
        cache.put('profile|k', {'entries': {'a': {'busbw_gbps': 1.0}}})
        assert os.path.exists(comms_cache)
        # Fresh read from disk = a new process.
        cache.forget_loaded()
        assert cache.get('profile|k')['entries']['a']['busbw_gbps'] \
            == 1.0
        data = json.load(open(comms_cache, encoding='utf-8'))
        assert data['kind'] == 'comms_profile'
        assert data['version'] == 1

    def test_corrupt_cold_start(self, comms_cache):
        with open(comms_cache, 'w', encoding='utf-8') as f:
            f.write('{"version": 1, "entr')   # torn write
        cache = comms_profile.get_cache()
        assert cache.get('profile|k') is None      # no raise
        cache.put('profile|k', {'entries': {}})    # recovers
        cache.forget_loaded()
        assert cache.get('profile|k') == {'entries': {}}

    def test_foreign_layout_cold_start(self, comms_cache):
        # An autotune-format file (valid JSON, no comms kind stamp)
        # must read as cold, not as a profile.
        with open(comms_cache, 'w', encoding='utf-8') as f:
            json.dump({'version': 1,
                       'entries': {'x': {'block_q': 256}}}, f)
        assert comms_profile.get_cache().get('x') is None

    def test_unwritable_path_in_memory_only(self, tmp_path):
        comms_profile.reset_for_tests()
        # A directory path: open() for read AND the atomic replace
        # both fail with OSError — load is a cold start, put keeps
        # the in-memory copy and never raises.
        cache = comms_profile.CommsProfileCache(str(tmp_path))
        cache.put('k', {'v': 1})
        assert cache.get('k') == {'v': 1}
        cache.forget_loaded()
        assert cache.get('k') is None   # nothing persisted

    def test_payload_sweep_env(self, monkeypatch):
        monkeypatch.setenv('SKYT_COMMS_PROBE_MB', '0.5, 2,8')
        assert comms_profile.payload_sweep_mb() == [0.5, 2.0, 8.0]
        monkeypatch.setenv('SKYT_COMMS_PROBE_MB', 'nope,-1')
        assert comms_profile.payload_sweep_mb() == \
            list(comms_profile.DEFAULT_PAYLOADS_MB)


# ------------------------------------------------------- link classes
class TestLinkClasses:
    def test_emulated_needs_hint(self):
        mesh = fake_mesh((2, 1, 2), ('dp', 'fsdp', 'tp'))
        assert comms_profile.axis_link_classes(mesh) == \
            {'dp': 'ici', 'tp': 'ici'}
        assert comms_profile.axis_link_classes(mesh, ('dp',)) == \
            {'dp': 'dcn', 'tp': 'ici'}

    def test_slice_index_detection(self):
        # dp-major over 2 slices of 2: walking dp changes slice.
        mesh = fake_mesh((2, 2), ('dp', 'tp'),
                         slice_of=lambda i: i // 2)
        assert comms_profile.axis_link_classes(mesh) == \
            {'dp': 'dcn', 'tp': 'ici'}


# ------------------------------------------------------------- probe
def _fake_bench(mesh, axis, op, payload_mb, iters=5, clock=None):
    # Deterministic synthetic measurement (no jit): bandwidth depends
    # only on (axis, op, payload).
    from skypilot_tpu.parallel import collectives
    n = mesh.shape[axis]
    t = 0.001 * (1 + len(op)) * payload_mb
    payload_bytes = payload_mb * 2 ** 20
    if op in ('all_gather', 'reduce_scatter'):
        payload_bytes *= n
    algbw = payload_bytes / t / 1e9
    return {'op': op, 'axis': axis, 'ranks': n,
            'payload_mb': payload_mb, 'time_ms': t * 1e3,
            'algbw_gbps': algbw,
            'busbw_gbps': algbw * collectives.busbw_factor(op, n)}


class TestProbe:
    def test_probe_deterministic_under_scripted_clock(self, comms_cache):
        mesh = fake_mesh((2, 2), ('dp', 'tp'))
        kw = dict(dcn_axes=('dp',), payloads_mb=[0.25, 1.0],
                  bench=_fake_bench)
        p1 = comms_profile.probe_mesh(mesh, clock=ScriptedClock(), **kw)
        p2 = comms_profile.probe_mesh(mesh, clock=ScriptedClock(), **kw)
        assert p1 == p2
        assert not p1['truncated']
        # 2 axes x 4 ops x 2 payloads
        assert len(p1['entries']) == 16
        e = p1['entries']['all_gather|dp|dcn|r2|mb1']
        assert e['link'] == 'dcn' and e['busbw_gbps'] > 0

    def test_probe_fault_descends_without_crash(self, comms_cache):
        mesh = fake_mesh((2,), ('tp',))
        faults.configure('comms.probe=error,where=op:all_gather')
        try:
            p = comms_profile.probe_mesh(
                mesh, payloads_mb=[1.0], bench=_fake_bench,
                clock=ScriptedClock())
            assert faults.fired_counts()[('comms.probe', 'error')] >= 1
        finally:
            faults.reset()
        ops = {e['op'] for e in p['entries'].values()}
        assert 'all_gather' not in ops
        assert {'all_reduce', 'reduce_scatter', 'ppermute'} <= ops

    def test_probe_budget_truncates_and_skips_persist(self, comms_cache):
        mesh = fake_mesh((2,), ('tp',))
        clock = ScriptedClock(dt=10.0)   # budget gone after one read
        profile, src = comms_profile.load_or_probe(
            mesh, payloads_mb=[1.0], bench=_fake_bench, clock=clock,
            budget_s=5.0)
        assert src == 'probed' and profile['truncated']
        # Truncated profiles must not be cached as the topology truth.
        assert comms_profile.load_cached(mesh) is None

    def test_pair_probe_targets_slice_pairs_not_positions(
            self, comms_cache, monkeypatch):
        """dcn_pairs must be keyed by SLICE index, not merged-axis
        position: a merged dcn-crossing axis with an ICI factor (e.g.
        dp = dcn4 x ici2 = 8) has intra-slice position pairs that are
        ICI hops — probing them as DCN costs would feed the advisor
        wrong bandwidths."""
        calls = []
        monkeypatch.setattr(
            comms_profile, '_probe_dcn_pairs',
            lambda mesh, axis, n_slices, **kw: calls.append(
                (axis, n_slices)) or {'0,1': {'busbw_gbps': 1.0}})
        # Real slices: 4 slices of 2 read off slice_index.
        mesh = fake_mesh((8,), ('dp',), slice_of=lambda i: i // 2)
        p = comms_profile.probe_mesh(mesh, payloads_mb=[1.0],
                                     bench=_fake_bench,
                                     clock=ScriptedClock())
        assert calls == [('dp', 4)]
        assert p['num_slices'] == 4 and p['dcn_pairs']
        # Emulated slices: the caller names the DCN factor.
        calls.clear()
        mesh = fake_mesh((8,), ('dp',))
        comms_profile.probe_mesh(mesh, dcn_axes=('dp',),
                                 payloads_mb=[1.0], num_slices=4,
                                 bench=_fake_bench,
                                 clock=ScriptedClock())
        assert calls == [('dp', 4)]
        # Two slices have no permutation freedom: no pair probe.
        calls.clear()
        p = comms_profile.probe_mesh(fake_mesh((2,), ('dp',)),
                                     dcn_axes=('dp',),
                                     payloads_mb=[1.0],
                                     bench=_fake_bench,
                                     clock=ScriptedClock())
        assert calls == [] and p['dcn_pairs'] == {}

    def test_load_or_probe_caches(self, comms_cache):
        mesh = fake_mesh((2, 2), ('dp', 'tp'))
        p1, src1 = comms_profile.load_or_probe(
            mesh, dcn_axes=('dp',), payloads_mb=[1.0],
            bench=_fake_bench, clock=ScriptedClock())
        assert src1 == 'probed'
        # Fresh process: the cache file answers, no re-probe.
        comms_profile.get_cache().forget_loaded()

        def _boom(*a, **k):
            raise AssertionError('re-probed despite cache hit')
        p2, src2 = comms_profile.load_or_probe(
            mesh, dcn_axes=('dp',), bench=_boom)
        assert src2 == 'cache'
        assert p2['entries'] == p1['entries']


# ------------------------------------------------------------ census
def _entry(op, axes, ranks, payload, count=1):
    return comms_census.CensusEntry(op=op, axes=tuple(axes),
                                    ranks=ranks, payload_bytes=payload,
                                    count=count)


class TestEstimate:
    def test_estimate_math_and_links(self):
        profile = {'entries': {
            'k1': {'op': 'all_gather', 'axis': 'dp', 'link': 'dcn',
                   'ranks': 2, 'payload_mb': 1.0, 'busbw_gbps': 2.0},
            'k2': {'op': 'all_reduce', 'axis': 'tp', 'link': 'ici',
                   'ranks': 2, 'payload_mb': 1.0, 'busbw_gbps': 10.0},
        }}
        entries = [_entry('all_gather', ('dp',), 2, 2 ** 20),
                   _entry('all_reduce', ('tp',), 2, 2 ** 20, count=3)]
        est = comms_census.estimate(entries, profile,
                                    dcn_axes=('dp',))
        # all_gather: payload * (n-1)/n / busbw
        want_dp = 2 ** 20 * 0.5 / 2e9
        assert est['dp']['link'] == 'dcn'
        assert est['dp']['seconds'] == pytest.approx(want_dp)
        assert est['dp']['bytes'] == 2 ** 20
        # all_reduce: payload * 2(n-1)/n / busbw, x3 sites
        want_tp = (2 ** 20) * 1.0 / 10e9 * 3
        assert est['tp']['link'] == 'ici'
        assert est['tp']['seconds'] == pytest.approx(want_tp)
        assert est['tp']['ops']['all_reduce']['count'] == 3

    def test_no_profile_reports_bytes_only(self):
        rep = comms_census.report([_entry('all_reduce', ('dp',), 2,
                                          1024)], 'stablehlo_lowered')
        assert rep['total_bytes'] == 1024
        assert rep['total_seconds'] is None
        assert 'dp' in comms_census.format_report(rep)

    def test_publish_metrics(self):
        reg = metrics_lib.MetricsRegistry()
        rep = comms_census.report(
            [_entry('all_reduce', ('dp',), 2, 1000)],
            'hlo_compiled',
            profile={'entries': {
                'k': {'op': 'all_reduce', 'axis': 'dp', 'link': 'ici',
                      'ranks': 2, 'payload_mb': 1.0,
                      'busbw_gbps': 1.0}}})
        comms_census.publish_metrics(rep, steps=10, registry=reg)
        expo = reg.expose()
        assert ('skyt_train_comm_bytes_total'
                '{axis="dp",op="all_reduce"} 10000') in expo
        assert 'skyt_train_comm_seconds_estimate{axis="dp"}' in expo

    def test_census_mode_env(self, monkeypatch):
        monkeypatch.setenv('SKYT_COMMS_CENSUS', 'off')
        assert comms_census.census_mode() == 'off'
        monkeypatch.setenv('SKYT_COMMS_CENSUS', 'compiled')
        assert comms_census.census_mode() == 'compiled'
        monkeypatch.setenv('SKYT_COMMS_CENSUS', 'bogus')
        assert comms_census.census_mode() == 'lowered'
        monkeypatch.delenv('SKYT_COMMS_CENSUS', raising=False)
        assert comms_census.census_mode() == 'lowered'


class TestCensusParsers:
    def test_hlo_iota_replica_groups(self):
        groups = comms_census._expand_iota_groups(
            4, 2, [2, 2, 2], [0, 2, 1])
        arr = np.arange(8).reshape(2, 2, 2).transpose(0, 2, 1)
        assert groups == arr.reshape(4, 2).tolist()

    def test_hlo_line_census(self):
        mesh = fake_mesh((1, 2, 1, 2, 1, 2),
                         ('pp', 'dp', 'cp', 'fsdp', 'ep', 'tp'))
        line = ('  %all-reduce.1 = f32[4,64]{1,0} all-reduce('
                'f32[4,64]{1,0} %x), channel_id=2, '
                'replica_groups=[4,2]<=[2,2,2]T(0,1,2), '
                'use_global_device_ids=true, to_apply=%add')
        entries = comms_census._census_hlo(line, mesh)
        assert len(entries) == 1
        e = entries[0]
        assert e.op == 'all_reduce' and e.axes == ('tp',)
        assert e.ranks == 2 and e.payload_bytes == 4 * 64 * 4

    def test_hlo_done_ops_skipped(self):
        mesh = fake_mesh((2,), ('dp',))
        text = ('  %ag = f32[8]{0} all-gather-start(f32[4]{0} %x), '
                'replica_groups={{0,1}}, dimensions={0}\n'
                '  %agd = f32[8]{0} all-gather-done(f32[8]{0} %ag)\n')
        entries = comms_census._census_hlo(text, mesh)
        assert len(entries) == 1 and entries[0].op == 'all_gather'
        assert entries[0].payload_bytes == 8 * 4   # gathered buffer

    def test_collective_permute_pairs(self):
        mesh = fake_mesh((2, 2), ('dp', 'tp'))
        line = ('  %cp = f32[4]{0} collective-permute(f32[4]{0} %x), '
                'channel_id=1, source_target_pairs={{0,2},{2,0}}')
        (e,) = comms_census._census_hlo(line, mesh)
        assert e.op == 'collective_permute' and e.axes == ('dp',)


# --------------------------------------------------- advisor/placement
HET_PAIRS = {   # slow links on (0,3) and (1,2); everything else fast
    '0,1': {'busbw_gbps': 10.0}, '0,2': {'busbw_gbps': 10.0},
    '0,3': {'busbw_gbps': 1.0}, '1,2': {'busbw_gbps': 1.0},
    '1,3': {'busbw_gbps': 10.0}, '2,3': {'busbw_gbps': 10.0}}
HET_PROFILE = {'entries': {}, 'dcn_pairs': HET_PAIRS}


class TestPlacementAdvisor:
    def test_picks_cheap_permutation(self):
        dec = comms_profile.choose_dcn_permutation(4, HET_PROFILE)
        # The only 4-ring avoiding both slow links is 0-1-3-2(-0).
        assert dec['perm'] == [0, 1, 3, 2]
        assert dec['score'] == pytest.approx(4 * 0.1)
        assert dec['rowmajor_score'] == pytest.approx(0.1 + 1 + 0.1 + 1)
        assert dec['score'] < dec['rowmajor_score']

    def test_no_profile_keeps_rowmajor_order(self):
        dec = comms_profile.choose_dcn_permutation(4, None)
        assert dec['perm'] == [0, 1, 2, 3]

    def test_two_slices_identity(self):
        dec = comms_profile.choose_dcn_permutation(2, HET_PROFILE)
        assert dec['perm'] == [0, 1]

    def test_cached_across_restart(self, comms_cache):
        # Production shape: the probed profile sits in the same cache
        # under its topology key; the placement winner is valid as
        # long as the profile it was scored against is.
        comms_profile.get_cache().put('profile|k', HET_PROFILE)
        perm = comms_profile.placement_for('k#spec', 4, HET_PROFILE)
        assert perm == [0, 1, 3, 2]
        comms_profile.get_cache().forget_loaded()
        # No profile handed in: cached profile + cached winner answer.
        assert comms_profile.placement_for('k#spec', 4) == [0, 1, 3, 2]

    def test_new_profile_invalidates_cached_winner(self, comms_cache):
        assert comms_profile.placement_for(
            'k#spec', 4, HET_PROFILE) == [0, 1, 3, 2]
        # Re-measured network: the slow links moved to the old cheap
        # ring's hops — the cached winner must NOT outlive the probe.
        flipped = {'entries': {}, 'dcn_pairs': {
            k: {'busbw_gbps': 11.0 - v['busbw_gbps']}
            for k, v in HET_PAIRS.items()}}
        perm2 = comms_profile.placement_for('k#spec', 4, flipped)
        assert perm2 == [0, 1, 2, 3]

    def test_bad_cached_entry_recomputes(self, comms_cache):
        comms_profile.get_cache().put('placement|k#spec',
                                      {'perm': [7, 7]})
        assert comms_profile.placement_for('k#spec', 4, HET_PROFILE) \
            == [0, 1, 3, 2]


@pytest.mark.heavy
class TestHybridMeshPlacement:
    def test_rowmajor_byte_identical_and_default(self, comms_cache):
        import jax

        from skypilot_tpu.parallel import mesh as mesh_lib
        ici = mesh_lib.MeshSpec(fsdp=2, tp=2)
        dcn = mesh_lib.MeshSpec(dp=2)
        base = mesh_lib.build_hybrid_mesh(ici, dcn, num_slices=2)
        explicit = mesh_lib.build_hybrid_mesh(ici, dcn, num_slices=2,
                                              placement='rowmajor')
        # Expected row-major chunk-interleave layout, computed
        # independently of build_hybrid_mesh: device order is
        # dp-major over contiguous 4-device slices, fsdp then tp
        # within a slice.
        want = np.array(jax.devices()[:8]).reshape(1, 2, 1, 2, 1, 2)
        for mesh in (base, explicit):
            assert (np.vectorize(id)(mesh.devices) ==
                    np.vectorize(id)(want)).all()

    def test_bad_placement_raises(self):
        from skypilot_tpu.parallel import mesh as mesh_lib
        with pytest.raises(ValueError, match='placement'):
            mesh_lib.build_hybrid_mesh(
                mesh_lib.MeshSpec(tp=4), mesh_lib.MeshSpec(dp=2),
                num_slices=2, placement='fancy')

    def test_measured_applies_cheap_slice_order(self, comms_cache):
        import jax

        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.build_hybrid_mesh(
            mesh_lib.MeshSpec(tp=2), mesh_lib.MeshSpec(dp=4),
            num_slices=4, placement='measured', profile=HET_PROFILE)
        got = [d.id for d in mesh.devices.reshape(-1)]
        # Slice groups [0,1],[2,3],[4,5],[6,7] in advisor order
        # [0, 1, 3, 2].
        assert got == [0, 1, 2, 3, 6, 7, 4, 5]
        # ICI layout inside each slice untouched: tp pairs stay
        # contiguous chunks.
        arr = mesh.devices
        for dpi in range(4):
            pair = [arr[0, dpi, 0, 0, 0, t].id for t in range(2)]
            assert pair[1] == pair[0] + 1

    def test_real_pair_probe_crosses_slice_boundaries(self,
                                                      comms_cache):
        """Real _probe_dcn_pairs on an 8-device dp axis with a
        4-slice DCN factor: 6 slice pairs (not 28 position pairs)."""
        import jax

        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(dp=8),
                                   jax.devices()[:8])
        pairs = comms_profile._probe_dcn_pairs(
            mesh, 'dp', 4, payload_mb=0.25, iters=1)
        assert sorted(pairs) == ['0,1', '0,2', '0,3', '1,2', '1,3',
                                 '2,3']
        assert all(v['busbw_gbps'] > 0 for v in pairs.values())

    def test_measured_without_profile_matches_rowmajor(self,
                                                       comms_cache):
        from skypilot_tpu.parallel import mesh as mesh_lib
        ici, dcn = mesh_lib.MeshSpec(tp=2), mesh_lib.MeshSpec(dp=4)
        row = mesh_lib.build_hybrid_mesh(ici, dcn, num_slices=4)
        measured = mesh_lib.build_hybrid_mesh(ici, dcn, num_slices=4,
                                              placement='measured')
        assert (np.vectorize(id)(row.devices) ==
                np.vectorize(id)(measured.devices)).all()


# ------------------------------------------- census on real programs
@pytest.mark.heavy
class TestCensusReal:
    def test_shardmap_lowered_census(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(dp=2, fsdp=2,
                                                     tp=2))

        def f(x):
            y = jax.lax.psum(x, 'tp')
            z = jax.lax.all_gather(x, 'fsdp')
            w = jax.lax.ppermute(x, 'dp', [(0, 1), (1, 0)])
            s = jax.lax.psum_scatter(x, 'tp', tiled=True)
            return (jnp.sum(y) + jnp.sum(z) + jnp.sum(w) +
                    jnp.sum(s[..., :1]))

        fn = jax.jit(mesh_lib.shard_map(f, mesh, in_specs=P('dp'),
                                        out_specs=P(),
                                        check_rep=False))
        x = jnp.ones((8, 4))
        entries, source = comms_census.census_step(fn, x, mesh=mesh)
        assert source == 'stablehlo_lowered'
        by_op = {e.op: e for e in entries}
        assert by_op['all_reduce'].axes == ('tp',)
        assert by_op['all_gather'].axes == ('fsdp',)
        assert by_op['collective_permute'].axes == ('dp',)
        assert by_op['reduce_scatter'].axes == ('tp',)
        # Per-shard payloads: x is [8,4] f32 over dp=2 -> [4,4].
        assert by_op['all_reduce'].payload_bytes == 4 * 4 * 4
        assert by_op['all_gather'].payload_bytes == 2 * 4 * 4 * 4

    @pytest.mark.parametrize('axis', ['dp', 'fsdp', 'tp'])
    def test_tiny_llama_census_attributes_right_axis(self, axis):
        """Golden counts on the tiny llama: with exactly one active
        mesh axis, every SPMD-inserted collective must attribute to
        that axis (compiled mode — pjit collectives don't exist at
        the lowered stage)."""
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.models import llama
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.train import trainer

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(**{axis: 2}), jax.devices()[:2])
        cfg = llama.CONFIGS['debug']
        model = llama.LlamaModel(cfg)
        tx = trainer.make_optimizer(trainer.TrainerConfig(
            warmup_steps=1, total_steps=4))
        sample = jnp.zeros((4, 64), jnp.int32)
        state, _ = trainer.create_sharded_state(
            model, tx, mesh, sample, jax.random.PRNGKey(0))
        step = trainer.make_train_step(model, tx, mesh, donate=False)
        data = {'tokens': sample, 'targets': sample}
        # Lowered mode on a pjit program: zero collectives, by design.
        low_entries, low_src = comms_census.census_step(
            step, state, data, mesh=mesh, mode='lowered')
        assert low_src == 'stablehlo_lowered' and low_entries == []
        entries, source = comms_census.census_step(
            step, state, data, mesh=mesh, mode='compiled')
        assert source == 'hlo_compiled'
        assert entries, 'SPMD inserted no collectives?'
        assert all(e.axes == (axis,) for e in entries), entries
        rep = comms_census.report(entries, source)
        assert rep['axes'][axis]['bytes'] > 0
        ops = set(rep['axes'][axis]['ops'])
        # Gradient sync rides all-reduce on every spec; fsdp's
        # parameter gathering adds all-gather.
        assert 'all_reduce' in ops
        if axis == 'fsdp':
            assert 'all_gather' in ops

    def test_pipeline_pp_census(self):
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.parallel import pipeline

        pp = 4
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(pp=pp),
                                   jax.devices()[:pp])
        dim, m, bm = 8, 8, 2

        def stage_fn(params, x):
            return jnp.tanh(x @ params['w'])

        stacked = {'w': jnp.ones((pp, dim, dim)) * 0.1}
        batch = jnp.ones((m * bm, dim))
        targets = jnp.zeros_like(batch)
        loss_fn = pipeline.pipeline_loss_fn(
            stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh,
            num_microbatches=m)
        grad_fn = jax.jit(jax.value_and_grad(loss_fn))
        entries, source = comms_census.census_step(
            grad_fn, stacked, batch, targets, mesh=mesh)
        assert source == 'stablehlo_lowered'
        ops = {e.op for e in entries}
        assert 'collective_permute' in ops   # the stage ring
        assert all(e.axes == ('pp',) for e in entries), entries


# --------------------------------------------------- /fleet/comms
EXPO_T0 = """\
# TYPE skyt_comms_probe_busbw_gbps gauge
skyt_comms_probe_busbw_gbps{axis="dp",op="all_gather",link="dcn"} 0.8
skyt_comms_probe_busbw_gbps{axis="tp",op="all_reduce",link="ici"} 42.0
# TYPE skyt_train_comm_seconds_estimate gauge
skyt_train_comm_seconds_estimate{axis="dp"} 0.0031
# TYPE skyt_train_comm_bytes_total counter
skyt_train_comm_bytes_total{axis="dp",op="all_gather"} 1000
"""
EXPO_T1 = EXPO_T0.replace(
    'skyt_train_comm_bytes_total{axis="dp",op="all_gather"} 1000',
    'skyt_train_comm_bytes_total{axis="dp",op="all_gather"} 61000')


class TestFleetComms:
    def _fleet(self, comms_cache):
        from skypilot_tpu.serve import fleet as fleet_lib

        class Clock:
            t = 1_000_000.0

            def __call__(self):
                return self.t
        clock = Clock()
        fl = fleet_lib.FleetTelemetry(
            'svc', metrics_registry=metrics_lib.MetricsRegistry(),
            clock=clock,
            http_get=lambda url, t: EXPO_T0)
        fl.ingest_text('r1', EXPO_T0)
        clock.t += 30
        fl.ingest_text('r1', EXPO_T1)
        return fl

    def test_comms_report(self, comms_cache):
        fl = self._fleet(comms_cache)
        rep = fl.comms_report(window_s=600)
        t = rep['targets']['r1']
        assert t['probe_busbw_gbps']['dp|all_gather|dcn'] == 0.8
        assert t['comm_seconds_estimate']['dp'] == 0.0031
        assert t['comm_bytes_per_s']['dp'] == pytest.approx(
            60000 / 600)
        # The local cached profile summary rides along.
        comms_profile.get_cache().put('profile|fake|d2|tp2i', {
            'entries': {'k': {'op': 'all_reduce', 'axis': 'tp',
                              'link': 'ici', 'ranks': 2,
                              'payload_mb': 1.0, 'busbw_gbps': 5.0}}})
        rep = fl.comms_report(window_s=600)
        assert rep['local_profiles']['fake|d2|tp2i'][
            'ici.all_reduce']['busbw_gbps'] == 5.0

    def test_route_contract(self, comms_cache):
        import asyncio

        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        from skypilot_tpu.serve import fleet as fleet_lib
        fl = self._fleet(comms_cache)

        async def run():
            app = web.Application()
            fleet_lib.add_fleet_routes(app, fl, lambda rid: None)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                resp = await client.get('/fleet/comms')
                assert resp.status == 200
                body = await resp.json()
                assert body['service'] == 'svc'
                assert 'r1' in body['targets']
                assert body['targets']['r1'][
                    'probe_busbw_gbps']['tp|all_reduce|ici'] == 42.0
                resp = await client.get('/fleet/comms',
                                        params={'window_s': '-3'})
                assert resp.status == 400
            finally:
                await client.close()

        asyncio.run(run())


# -------------------------------------------------- collectives CLI
@pytest.mark.heavy
class TestCollectivesCli:
    def test_json_artifact_ok(self, tmp_path):
        from skypilot_tpu.parallel import collectives
        out = tmp_path / 'collectives.json'
        collectives.main(['--axis', 'tp', '--mb', '0.05', '--iters',
                          '2', '--ops', 'all_reduce', '--json',
                          str(out)])
        data = json.loads(out.read_text())
        assert data['status'] == 'ok'
        assert data['payload_mib'] == 0.05
        (r,) = data['results']
        assert r['op'] == 'all_reduce' and r['ranks'] == 8
        assert r['busbw_gbps'] > 0

    def test_mib_payload_rounding(self):
        """bench_collective sizes payloads in MiB: 1 MiB over 8 ranks
        = 2**20/4 f32 elements, rounded to a multiple of n."""
        import jax

        from skypilot_tpu.parallel import collectives
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(tp=2),
                                   jax.devices()[:2])
        r = collectives.bench_collective(mesh, 'tp', 'ppermute',
                                         payload_mb=1.0, iters=1)
        # per-rank buffer for ppermute = elems*4 bytes = 1 MiB exactly
        # (2**20/4 divisible by 2).
        assert r['payload_mb'] == 1.0
        assert r['algbw_gbps'] * r['time_ms'] * 1e6 == pytest.approx(
            2 ** 20, rel=1e-6)


# -------------------------------------------------------- sft e2e
@pytest.mark.heavy
def test_sft_logs_comms_census_on_hybrid_mesh(tmp_path, monkeypatch):
    """CPU end-to-end acceptance: a multislice (emulated 2-slice) sft
    run logs the per-axis comms breakdown next to MFU, publishes the
    comm metric families, and lands the report in the postmortem live
    state / train.steps span attrs path."""
    import io
    import logging

    monkeypatch.setenv('SKYT_COMMS_CACHE',
                       str(tmp_path / 'comms.json'))
    monkeypatch.setenv('SKYT_COMMS_CENSUS', 'compiled')
    monkeypatch.setenv('SKYT_WATCHDOG', '0')
    comms_profile.reset_for_tests()
    from skypilot_tpu.train import sft

    # The framework logger does not propagate to pytest's caplog
    # handler; attach one directly.
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    sft.logger.addHandler(handler)
    try:
        sft.main(['--model', 'debug', '--mesh', 'fsdp=2,tp=2',
                  '--dcn-mesh', 'dp=2', '--steps', '2', '--batch',
                  '4', '--seq', '64', '--log-every', '1',
                  '--prefetch', '0'])
    finally:
        sft.logger.removeHandler(handler)
    text = buf.getvalue()
    assert 'comms census (hlo_compiled' in text
    assert 'dcn' in text.split('comms census')[1].splitlines()[0]
    expo = metrics_lib.REGISTRY.expose()
    assert 'skyt_train_comm_bytes_total{axis="' in expo
    comms_profile.reset_for_tests()
