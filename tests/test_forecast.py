"""Predictive autoscaling (docs/serving.md "Elastic capacity").

Forecaster half: deterministic fake-clock fits over synthetic demand
(constant, seasonal, gappy), honest out-of-sample error tracking, the
`forecast.fit` fault blowing the error bound (and clean fits decaying
it back), and the bounded drop-oldest history buffer. Autoscaler
half: the PredictiveAutoscaler wrapper — prescale raises the reactive
target ahead of the wave, untrusted forecasts degrade to exactly the
reactive decision, and `make_autoscaler` returns the bare reactive
instance unless SKYT_AUTOSCALE_PREDICT=1.
"""

import pytest

from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import forecast
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _feed(fc, buckets, per_bucket=4, start=0):
    """`per_bucket` events in each of `buckets` consecutive 1s buckets."""
    for b in range(start, start + buckets):
        for i in range(per_bucket):
            fc.observe(b + (i + 0.5) / (per_bucket + 1))


def _forecaster(clock, **kw):
    kw.setdefault('bucket_s', 1.0)
    kw.setdefault('season_buckets', 5)
    return forecast.DemandForecaster(clock=clock, **kw)


# ------------------------------------------------------------ forecaster
def test_constant_demand_fit_and_predict():
    """Constant 4 req/s: the forecast converges to 4 qps at any
    horizon, the out-of-sample error goes to ~0, and healthy() flips
    once SKYT_FORECAST_MIN_BUCKETS completed buckets are fitted."""
    clock = _Clock()
    fc = _forecaster(clock)
    assert fc.predict_qps(60.0) == 0.0       # nothing fitted yet
    assert not fc.healthy()
    _feed(fc, buckets=20, per_bucket=4)
    clock.t = 20.0
    assert fc.fit()
    assert fc.fitted_buckets == 20
    assert fc.rel_err is not None and fc.rel_err < 0.05
    assert fc.healthy()
    for horizon in (0.0, 10.0, 60.0):
        assert fc.predict_qps(horizon) == pytest.approx(4.0, rel=0.1)
    st = fc.status()
    assert st['healthy'] and st['fitted_buckets'] == 20
    assert st['dropped_points'] == 0 and st['fit_errors'] == 0


def test_min_buckets_gate():
    """Too little history is never trusted, even with perfect error."""
    clock = _Clock()
    fc = _forecaster(clock)
    _feed(fc, buckets=4, per_bucket=4)
    clock.t = 4.0
    assert fc.fit()
    assert fc.fitted_buckets == 4
    assert not fc.healthy()      # < SKYT_FORECAST_MIN_BUCKETS (8)


def test_seasonal_pattern_is_learned():
    """Alternating 8/0 demand with season=2: the seasonal component
    separates the even-bucket forecast from the odd-bucket one."""
    clock = _Clock()
    fc = _forecaster(clock, season_buckets=2)
    for b in range(20):
        if b % 2 == 0:
            for i in range(8):
                fc.observe(b + (i + 0.5) / 9)
    clock.t = 20.0
    assert fc.fit()
    high = fc.predict_qps(0.0)    # bucket 20: even slot
    low = fc.predict_qps(1.0)     # bucket 21: odd slot
    assert high > low + 2.0, (high, low)


def test_gaps_fold_as_zero_demand():
    """Silence is data: a gap folds in as true zero-demand buckets, so
    the level decays instead of freezing at the last busy bucket."""
    clock = _Clock()
    fc = _forecaster(clock)
    _feed(fc, buckets=1, per_bucket=6)
    clock.t = 10.0
    assert fc.fit()
    assert fc.fitted_buckets == 10    # bucket 0 busy + 9 silent
    assert fc.predict_qps(0.0) < 1.0


def test_incremental_fits_are_equivalent_to_one_shot():
    """fit() called every bucket and fit() called once at the end fold
    the same state — the fold is per-completed-bucket, not per-call."""
    c1, c2 = _Clock(), _Clock()
    one, inc = _forecaster(c1), _forecaster(c2)
    _feed(one, buckets=12, per_bucket=3)
    c1.t = 12.0
    one.fit()
    for b in range(12):
        for i in range(3):
            inc.observe(b + (i + 0.5) / 4)
        c2.t = b + 1.0
        inc.fit()
    assert inc.fitted_buckets == one.fitted_buckets == 12
    assert inc.predict_qps(5.0) == pytest.approx(one.predict_qps(5.0))


def test_history_buffer_drop_oldest(monkeypatch):
    """The raw-point buffer is bounded: overflow drops the OLDEST
    points and counts them — memory is O(cap) no matter the flood."""
    monkeypatch.setenv('SKYT_FORECAST_MAX_POINTS', '10')
    clock = _Clock()
    fc = _forecaster(clock)
    for i in range(25):
        fc.observe(float(i))
    assert fc.dropped_points == 15
    assert len(fc._pending) == 10
    assert min(fc._pending) == 15.0   # oldest gone, newest kept
    # observe_count floods respect the same cap.
    fc.observe_count(30.0, 100)
    assert len(fc._pending) == 10
    assert fc.dropped_points == 115


def test_fit_fault_blows_error_bound_then_decays_back(monkeypatch):
    """`forecast.fit=error` degrades honestly: rel_err jumps past the
    bound (healthy() False -> reactive fallback upstream) and decays
    back under it only after sustained clean fits."""
    clock = _Clock()
    fc = _forecaster(clock)
    _feed(fc, buckets=12, per_bucket=4)
    clock.t = 12.0
    assert fc.fit() and fc.healthy()
    faults.configure('forecast.fit=error,count=1')
    assert fc.fit() is False
    assert fc.fit_errors == 1
    assert fc.rel_err >= forecast.err_bound() * 4.0
    assert not fc.healthy()
    # Clean buckets keep arriving; the EWMA decays the blown estimate
    # back under the bound — the degradation self-heals.
    _feed(fc, buckets=15, per_bucket=4, start=12)
    clock.t = 27.0
    assert fc.fit()
    assert fc.healthy(), fc.status()


# ------------------------------------------- predictive autoscaler wrapper
def _spec(**kw):
    base = dict(readiness_path='/', min_replicas=1, max_replicas=10,
                target_qps_per_replica=1.0, upscale_delay_seconds=300,
                downscale_delay_seconds=300)
    base.update(kw)
    return spec_lib.ServiceSpec(**base)


def _predictive(monkeypatch, clock, spec=None):
    monkeypatch.setenv('SKYT_FORECAST_BUCKET_S', '1')
    monkeypatch.setenv('SKYT_FORECAST_SEASON_BUCKETS', '5')
    reg = metrics_lib.MetricsRegistry()
    inner = autoscalers.RequestRateAutoscaler(spec or _spec())
    return autoscalers.PredictiveAutoscaler(
        inner, metrics_registry=reg, clock=clock), inner, reg


def test_prescale_raises_target_ahead_of_reactive(monkeypatch):
    """A trusted 4-qps forecast prescales to 4 replicas while the
    reactive path (long upscale delay, stale window) still says 1 —
    and the reactive state is synced so it reasons from the new
    target."""
    clock = _Clock()
    a, inner, reg = _predictive(monkeypatch, clock)
    ts = [b + (i + 0.5) / 5 for b in range(12) for i in range(4)]
    a.collect_request_timestamps(ts)
    clock.t = 12.0
    d = a.evaluate_scaling(num_ready=1)
    assert d.target_num_replicas == 4, d
    assert 'prescale' in d.reason
    assert inner.target_num_replicas == 4
    assert a.last_decision['kind'] == 'prescale'
    dec = reg.counter('skyt_autoscaler_forecast_decisions_total', '',
                      ('decision',))
    assert dec.value('prescale') == 1
    assert reg.gauge('skyt_autoscaler_forecast_mode', '').value() == 1
    st = a.status()
    assert st['mode'] == 'predictive'
    assert st['forecast']['qps_at_lead'] == pytest.approx(4.0, rel=0.1)
    assert 'total' in st['forecast']['curves']


def test_untrusted_forecast_degrades_to_reactive(monkeypatch):
    """Insufficient history: the decision IS the inner reactive
    decision, counted as reactive_fallback with mode gauge 0."""
    clock = _Clock()
    a, inner, reg = _predictive(monkeypatch, clock)
    a.collect_request_timestamps([0.1, 0.2])   # 1 completed bucket
    clock.t = 2.0
    d = a.evaluate_scaling(num_ready=1)
    assert d.target_num_replicas == inner.target_num_replicas == 1
    dec = reg.counter('skyt_autoscaler_forecast_decisions_total', '',
                      ('decision',))
    assert dec.value('reactive_fallback') == 1
    assert reg.gauge('skyt_autoscaler_forecast_mode', '').value() == 0
    assert a.status()['mode'] == 'reactive'


def test_fit_fault_forces_reactive_and_counts(monkeypatch):
    """An injected forecast.fit failure on an otherwise-healthy
    forecaster degrades THAT evaluation to reactive and lands in
    skyt_autoscaler_forecast_fit_errors_total."""
    clock = _Clock()
    a, _inner, reg = _predictive(monkeypatch, clock)
    ts = [b + (i + 0.5) / 5 for b in range(12) for i in range(4)]
    a.collect_request_timestamps(ts)
    clock.t = 12.0
    assert a.evaluate_scaling(1).target_num_replicas == 4
    faults.configure('forecast.fit=error,count=1')
    d = a.evaluate_scaling(num_ready=4)
    assert d.target_num_replicas == 4   # reactive target, pre-synced
    dec = reg.counter('skyt_autoscaler_forecast_decisions_total', '',
                      ('decision',))
    assert dec.value('reactive_fallback') == 1
    errs = reg.counter('skyt_autoscaler_forecast_fit_errors_total', '')
    assert errs.value() == 1


def test_dropped_points_land_in_metrics(monkeypatch):
    monkeypatch.setenv('SKYT_FORECAST_MAX_POINTS', '8')
    clock = _Clock()
    a, _inner, reg = _predictive(monkeypatch, clock)
    a.collect_request_timestamps([float(i) / 10 for i in range(30)])
    clock.t = 3.0
    a.evaluate_scaling(1)
    dropped = reg.counter(
        'skyt_autoscaler_forecast_dropped_points_total', '')
    assert dropped.value() == 22
    # Delta-folded: a second tick with no new drops adds nothing.
    a.evaluate_scaling(1)
    assert dropped.value() == 22


def test_forecast_never_lowers_the_target(monkeypatch):
    """Safety contract: predictive only RAISES. A forecast below the
    reactive target is a hold, not a downscale."""
    clock = _Clock()
    spec = _spec(min_replicas=3)
    a, inner, reg = _predictive(monkeypatch, clock, spec=spec)
    ts = [b + (i + 0.5) / 3 for b in range(12) for i in range(2)]
    a.collect_request_timestamps(ts)    # 2 qps < min_replicas 3
    clock.t = 12.0
    d = a.evaluate_scaling(num_ready=3)
    assert d.target_num_replicas == 3
    dec = reg.counter('skyt_autoscaler_forecast_decisions_total', '',
                      ('decision',))
    assert dec.value('hold') == 1
    assert inner.target_num_replicas == 3


def test_fleet_ring_fallback_intake(monkeypatch):
    """With no LB delivering raw timestamps, demand is synthesized
    from the fleet rollup's skyt_lb_requests_total delta; the first
    direct timestamp batch switches intake off the fleet path."""
    class _FakeFleet:
        def __init__(self):
            self.calls = 0

        def sum_delta(self, name, labels, window, now=None):
            del name, labels, window, now
            self.calls += 1
            return 12.0

    clock = _Clock()
    monkeypatch.setenv('SKYT_FORECAST_BUCKET_S', '1')
    reg = metrics_lib.MetricsRegistry()
    inner = autoscalers.RequestRateAutoscaler(_spec())
    a = autoscalers.PredictiveAutoscaler(inner, fleet=_FakeFleet(),
                                         metrics_registry=reg,
                                         clock=clock)
    a.evaluate_scaling(1)         # first tick only arms the window
    clock.t = 1.0
    a.evaluate_scaling(1)
    assert a._curves['total'].fitted_buckets + \
        len(a._curves['total']._pending) >= 12
    a.collect_request_timestamps([1.5])
    clock.t = 2.0
    fleet = a._fleet
    before = fleet.calls
    a.evaluate_scaling(1)
    assert fleet.calls == before  # direct timestamps win


def test_qos_class_curves_feed_weighted_forecast(monkeypatch):
    """collect_qos tees per-class curves; once a class curve is
    healthy the forecast is the weight-combined class sum (batch
    discounted), visible per class in the qps gauge."""
    clock = _Clock()
    a, _inner, reg = _predictive(monkeypatch, clock)
    demand = [(b + (i + 0.5) / 5, 'interactive')
              for b in range(12) for i in range(4)]
    a.collect_qos(demand, sheds=[])
    # The trusted gate rides the TOTAL curve — feed it too (the real
    # LB sync always delivers both streams).
    a.collect_request_timestamps([t for t, _ in demand])
    clock.t = 12.0
    a.evaluate_scaling(1)
    assert 'interactive' in a._curves
    qps = reg.gauge('skyt_autoscaler_forecast_qps', '', ('class',))
    assert qps.value('interactive') == pytest.approx(4.0, rel=0.15)


def test_make_autoscaler_gating(monkeypatch):
    """SKYT_AUTOSCALE_PREDICT unset/0 -> the bare reactive instance
    (byte-for-byte existing behavior); =1 -> the predictive wrapper
    around the same pick."""
    monkeypatch.delenv('SKYT_AUTOSCALE_PREDICT', raising=False)
    a = autoscalers.make_autoscaler(_spec())
    assert isinstance(a, autoscalers.RequestRateAutoscaler)
    monkeypatch.setenv('SKYT_AUTOSCALE_PREDICT', '0')
    a = autoscalers.make_autoscaler(_spec())
    assert isinstance(a, autoscalers.RequestRateAutoscaler)
    monkeypatch.setenv('SKYT_AUTOSCALE_PREDICT', '1')
    a = autoscalers.make_autoscaler(_spec())
    assert isinstance(a, autoscalers.PredictiveAutoscaler)
    assert isinstance(a.inner, autoscalers.RequestRateAutoscaler)
    st = a.status()
    assert st['class'].startswith('Predictive(')


def test_reactive_status_has_mode_and_last_decision():
    """The base reactive autoscaler self-reports for `serve status` /
    /controller/status even without the predictive wrapper."""
    a = autoscalers.RequestRateAutoscaler(_spec())
    st = a.status()
    assert st['mode'] == 'reactive'
    assert st['target_num_replicas'] == 1
    a.evaluate_scaling(1)
    assert a.status()['last_decision'] is not None


def test_target_ceiling_respects_max_replicas(monkeypatch):
    """A huge forecast clamps at max_replicas, never past it."""
    clock = _Clock()
    a, _inner, _reg = _predictive(
        monkeypatch, clock, spec=_spec(max_replicas=3))
    ts = [b + (i + 0.5) / 41 for b in range(12) for i in range(40)]
    a.collect_request_timestamps(ts)
    clock.t = 12.0
    d = a.evaluate_scaling(1)
    assert d.target_num_replicas == 3
