"""Pallas paged-attention kernel vs the gather-based XLA reference
(interpret mode on CPU; tests_tpu/ compiles it on the chip)."""
import numpy as np
import pytest

import jax.numpy as jnp

from skypilot_tpu.infer.paged_cache import PagePool
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops import paged_attention

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


def _setup(slots=3, hq=4, hkv=2, d=64, n_pages=9, p=16, mp=4, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(slots, hq, d)), jnp.float32)
    k_pool = jnp.asarray(rng.normal(size=(n_pages, hkv, p, d)),
                         jnp.float32)
    v_pool = jnp.asarray(rng.normal(size=(n_pages, hkv, p, d)),
                         jnp.float32)
    return q, k_pool, v_pool


def _reference(q, k_pool, v_pool, tables, lengths):
    """Gather view + masked reference attention (the XLA decode path)."""
    k_view = PagePool.gather_view_layer(k_pool, tables)  # [S, mp*P, H, d]
    v_view = PagePool.gather_view_layer(v_pool, tables)
    out = attention_ops.mha_reference(
        q[:, None], k_view, v_view,
        q_positions=lengths[:, None])
    return out[:, 0]


class TestPagedDecodeAttention:
    def test_matches_reference_varied_lengths(self):
        q, k_pool, v_pool = _setup()
        tables = jnp.asarray([[1, 2, 3, 0],
                              [4, 5, 0, 0],
                              [6, 7, 8, 0]], jnp.int32)
        lengths = jnp.asarray([40, 17, 33], jnp.int32)
        out = paged_attention.paged_decode_attention(
            q, k_pool, v_pool, tables, lengths)
        ref = _reference(q, k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_token_length_zero(self):
        """A slot at position 0 attends exactly its own KV row."""
        q, k_pool, v_pool = _setup(slots=1)
        tables = jnp.asarray([[2, 0, 0, 0]], jnp.int32)
        lengths = jnp.asarray([0], jnp.int32)
        out = paged_attention.paged_decode_attention(
            q, k_pool, v_pool, tables, lengths)
        # softmax over one position == that position's V.
        hkv = v_pool.shape[1]
        g = q.shape[1] // hkv
        expect = jnp.repeat(v_pool[2, :, 0], g, axis=0)  # [Hq, d]
        np.testing.assert_allclose(np.asarray(out[0]),
                                   np.asarray(expect), atol=2e-5)

    def test_gqa_groups(self):
        q, k_pool, v_pool = _setup(hq=8, hkv=2)
        tables = jnp.asarray([[1, 2, 0, 0]] * 3, jnp.int32)
        lengths = jnp.asarray([20, 5, 31], jnp.int32)
        out = paged_attention.paged_decode_attention(
            q, k_pool, v_pool, tables, lengths)
        ref = _reference(q, k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_dummy_page_rows_are_finite(self):
        """A released slot (all-zero table row, stale huge length) must
        produce finite garbage, not NaN/inf (its output is discarded)."""
        q, k_pool, v_pool = _setup()
        tables = jnp.asarray([[1, 2, 3, 0],
                              [0, 0, 0, 0],       # released slot
                              [4, 5, 0, 0]], jnp.int32)
        lengths = jnp.asarray([10, 9999, 20], jnp.int32)
        out = paged_attention.paged_decode_attention(
            q, k_pool, v_pool, tables, lengths)
        assert bool(jnp.isfinite(out).all())
        # Active slots still correct.
        ref = _reference(q, k_pool, v_pool, tables, lengths)
        for i in (0, 2):
            np.testing.assert_allclose(np.asarray(out[i]),
                                       np.asarray(ref[i]),
                                       atol=2e-5, rtol=2e-5)

    def test_bf16(self):
        q, k_pool, v_pool = _setup()
        q = q.astype(jnp.bfloat16)
        k_pool = k_pool.astype(jnp.bfloat16)
        v_pool = v_pool.astype(jnp.bfloat16)
        tables = jnp.asarray([[1, 2, 3, 0],
                              [4, 5, 0, 0],
                              [6, 7, 8, 0]], jnp.int32)
        lengths = jnp.asarray([40, 17, 33], jnp.int32)
        out = paged_attention.paged_decode_attention(
            q, k_pool, v_pool, tables, lengths)
        ref = _reference(q, k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)


class TestPagedDecodeAttentionMQ:
    """Multi-query (speculative verify) variant vs the gather reference:
    T consecutive tokens per slot at positions lengths[s]..+T-1."""

    def _mq_setup(self, slots=3, t=4, hq=4, hkv=2, d=64, n_pages=12,
                  p=16, seed=1):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(slots, t, hq, d)), jnp.float32)
        k_pool = jnp.asarray(rng.normal(size=(n_pages, hkv, p, d)),
                             jnp.float32)
        v_pool = jnp.asarray(rng.normal(size=(n_pages, hkv, p, d)),
                             jnp.float32)
        return q, k_pool, v_pool

    def _mq_reference(self, q, k_pool, v_pool, tables, lengths):
        t = q.shape[1]
        k_view = PagePool.gather_view_layer(k_pool, tables)
        v_view = PagePool.gather_view_layer(v_pool, tables)
        positions = lengths[:, None] + jnp.arange(t)[None, :]
        return attention_ops.mha_reference(q, k_view, v_view,
                                           q_positions=positions)

    def test_matches_reference_varied_lengths(self):
        q, k_pool, v_pool = self._mq_setup()
        tables = jnp.asarray([[1, 2, 3, 11],
                              [4, 5, 0, 0],
                              [6, 7, 8, 9]], jnp.int32)
        # Run straddles a page boundary for slot 0 (len 14, T=4 -> 18).
        lengths = jnp.asarray([14, 17, 33], jnp.int32)
        out = paged_attention.paged_decode_attention_mq(
            q, k_pool, v_pool, tables, lengths)
        ref = self._mq_reference(q, k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_t1_matches_single_query_kernel(self):
        q, k_pool, v_pool = self._mq_setup(t=1)
        tables = jnp.asarray([[1, 2, 0, 0],
                              [3, 0, 0, 0],
                              [4, 5, 6, 0]], jnp.int32)
        lengths = jnp.asarray([20, 3, 40], jnp.int32)
        out = paged_attention.paged_decode_attention_mq(
            q, k_pool, v_pool, tables, lengths)
        ref = paged_attention.paged_decode_attention(
            q[:, 0], k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(np.asarray(out[:, 0]),
                                   np.asarray(ref), atol=2e-5,
                                   rtol=2e-5)

    def test_causal_within_run(self):
        """Token 0 of the run must NOT see tokens 1..T-1's KV rows (the
        pool is random everywhere, so any causal leak — token 0
        attending positions lengths[s]+1.. — diverges from the
        single-query kernel's output, which by construction only
        attends <= lengths[s])."""
        q, k_pool, v_pool = self._mq_setup(slots=1, t=3)
        tables = jnp.asarray([[2, 3, 0, 0]], jnp.int32)
        lengths = jnp.asarray([10], jnp.int32)
        out = paged_attention.paged_decode_attention_mq(
            q, k_pool, v_pool, tables, lengths)
        single = paged_attention.paged_decode_attention(
            q[:, 0], k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(np.asarray(out[:, 0]),
                                   np.asarray(single), atol=2e-5,
                                   rtol=2e-5)

    def test_bf16_gqa(self):
        q, k_pool, v_pool = self._mq_setup(t=4, hq=8, hkv=2, seed=2)
        q = q.astype(jnp.bfloat16)
        k_pool = k_pool.astype(jnp.bfloat16)
        v_pool = v_pool.astype(jnp.bfloat16)
        tables = jnp.asarray([[1, 2, 3, 4],
                              [5, 6, 0, 0],
                              [7, 8, 9, 10]], jnp.int32)
        lengths = jnp.asarray([50, 20, 35], jnp.int32)
        out = paged_attention.paged_decode_attention_mq(
            q, k_pool, v_pool, tables, lengths)
        ref = self._mq_reference(q, k_pool, v_pool, tables, lengths)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)
