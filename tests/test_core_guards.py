"""Cloud-capability guards on cluster lifecycle ops (reference:
CloudImplementationFeatures, sky/clouds/cloud.py:27; TPU-pod stop block
sky/clouds/gcp.py:184-190)."""
import pytest

from skypilot_tpu import core, exceptions
from skypilot_tpu import resources as resources_lib


class _FakeHandle:
    def __init__(self, res):
        self.cluster_name = 'c'
        self.launched_resources = res


def _patch_handle(monkeypatch, res):
    monkeypatch.setattr(core, '_handle_or_raise',
                        lambda name: _FakeHandle(res))
    calls = []

    class _FakeBackend:
        def teardown(self, handle, terminate=False, purge=False):
            calls.append(('teardown', terminate))

        def set_autostop(self, handle, idle, down):
            calls.append(('autostop', idle, down))

    monkeypatch.setattr(core, '_backend', lambda: _FakeBackend())
    return calls


def test_stop_blocked_for_tpu_pod(monkeypatch, tmp_state_dir):
    res = resources_lib.Resources(cloud='gcp',
                                  accelerators='tpu-v5e-16')
    calls = _patch_handle(monkeypatch, res)
    with pytest.raises(exceptions.NotSupportedError):
        core.stop('c')
    assert not calls


def test_stop_allowed_for_single_host_tpu(monkeypatch, tmp_state_dir):
    res = resources_lib.Resources(cloud='gcp', accelerators='tpu-v5e-4')
    calls = _patch_handle(monkeypatch, res)
    core.stop('c')
    assert calls == [('teardown', False)]


def test_autostop_stop_mode_blocked_for_pod(monkeypatch, tmp_state_dir):
    res = resources_lib.Resources(cloud='gcp',
                                  accelerators='tpu-v5e-16')
    calls = _patch_handle(monkeypatch, res)
    with pytest.raises(exceptions.NotSupportedError):
        core.autostop('c', 10, down=False)
    # Autodown is fine (delete is always supported).
    core.autostop('c', 10, down=True)
    assert calls == [('autostop', 10, True)]


def test_autostop_cancel_never_blocked(monkeypatch, tmp_state_dir):
    res = resources_lib.Resources(cloud='gcp',
                                  accelerators='tpu-v5e-16')
    calls = _patch_handle(monkeypatch, res)
    core.autostop('c', -1, down=False)
    assert calls == [('autostop', -1, False)]
