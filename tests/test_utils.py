"""Tests for cross-cutting utils: command runners, config, subprocess."""

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu import skyt_config
from skypilot_tpu.utils import command_runner
from skypilot_tpu.utils import subprocess_utils


class TestLocalProcessRunner:

    def test_run_basic(self, tmp_path):
        r = command_runner.LocalProcessRunner(str(tmp_path / 'host0'))
        assert r.run('true') == 0
        assert r.run('false') == 1

    def test_home_remap(self, tmp_path):
        host = tmp_path / 'host0'
        r = command_runner.LocalProcessRunner(str(host))
        code, out, _ = r.run('echo $HOME', require_outputs=True)
        assert code == 0
        assert out.strip() == str(host)

    def test_env_and_cwd(self, tmp_path):
        host = tmp_path / 'h'
        sub = host / 'subdir'
        sub.mkdir(parents=True)
        r = command_runner.LocalProcessRunner(str(host))
        code, out, _ = r.run('echo $FOO-$(pwd)', env={'FOO': 'bar'},
                             cwd=str(sub), require_outputs=True)
        assert out.strip() == f'bar-{sub}'

    def test_log_path(self, tmp_path):
        r = command_runner.LocalProcessRunner(str(tmp_path / 'h'))
        log = tmp_path / 'out.log'
        assert r.run('echo hello', log_path=str(log)) == 0
        assert 'hello' in log.read_text()

    def test_rsync_up_down(self, tmp_path):
        src = tmp_path / 'src'
        src.mkdir()
        (src / 'a.txt').write_text('data')
        host = tmp_path / 'h'
        r = command_runner.LocalProcessRunner(str(host))
        r.rsync(str(src) + '/', str(host / 'dst'), up=True)
        assert (host / 'dst' / 'a.txt').read_text() == 'data'
        r.rsync(str(host / 'dst') + '/', str(tmp_path / 'back'), up=False)
        assert (tmp_path / 'back' / 'a.txt').read_text() == 'data'

    def test_run_or_raise(self, tmp_path):
        r = command_runner.LocalProcessRunner(str(tmp_path / 'h'))
        assert r.run_or_raise('echo ok', 'should not fail').strip() == 'ok'
        with pytest.raises(exceptions.CommandError):
            r.run_or_raise('exit 3', 'expected failure')


class TestSSHCommandBuild:

    def test_ssh_base_options(self, tmp_path):
        key = tmp_path / 'key'
        key.write_text('')
        r = command_runner.SSHCommandRunner('10.0.0.1', 'ubuntu', str(key),
                                            ssh_control_name='abc')
        base = r._ssh_base()
        assert 'ssh' == base[0]
        assert '-i' in base and str(key) in base
        joined = ' '.join(base)
        assert 'StrictHostKeyChecking=no' in joined
        assert 'ControlMaster=auto' in joined

    def test_proxy_command(self, tmp_path):
        key = tmp_path / 'key'
        key.write_text('')
        r = command_runner.SSHCommandRunner(
            '10.0.0.1', 'ubuntu', str(key),
            ssh_proxy_command='corkscrew proxy 8080 %h %p')
        assert any('ProxyCommand=corkscrew' in a for a in r._ssh_base())


class TestConfig:

    def test_missing_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv('SKYT_CONFIG', str(tmp_path / 'nope.yaml'))
        skyt_config.reload_for_testing()
        assert not skyt_config.loaded()
        assert skyt_config.get_nested(('gcp', 'project_id'), 'dflt') == 'dflt'

    def test_nested_get_set(self, tmp_path, monkeypatch):
        cfg = tmp_path / 'config.yaml'
        cfg.write_text('gcp:\n  project_id: proj-1\n  zone: us-central2-b\n')
        monkeypatch.setenv('SKYT_CONFIG', str(cfg))
        skyt_config.reload_for_testing()
        assert skyt_config.loaded()
        assert skyt_config.get_nested(('gcp', 'project_id')) == 'proj-1'
        assert skyt_config.get_nested(('gcp', 'missing'), 42) == 42
        updated = skyt_config.set_nested(('jobs', 'controller', 'cpus'), 8)
        assert updated['jobs']['controller']['cpus'] == 8
        # set_nested must not mutate the loaded config.
        assert skyt_config.get_nested(('jobs',)) is None


class TestSubprocessUtils:

    def test_run_in_parallel(self):
        out = subprocess_utils.run_in_parallel(lambda x: x * 2, [1, 2, 3])
        assert out == [2, 4, 6]

    def test_run_raises(self):
        with pytest.raises(exceptions.CommandError):
            subprocess_utils.run('exit 7')

    def test_kill_process_tree(self):
        import subprocess
        import time
        proc = subprocess.Popen(['bash', '-c', 'sleep 100 & sleep 100'])
        time.sleep(0.2)
        subprocess_utils.kill_process_tree(proc.pid)
        time.sleep(0.2)
        assert proc.poll() is not None


class TestTimeline:

    def test_enabled_tracks_env(self, monkeypatch):
        """SKYT_DEBUG is re-read per event: toggling it mid-process
        (long-lived servers, tests) enables/disables tracing without a
        restart — the old first-call cache pinned the initial value."""
        from skypilot_tpu.utils import timeline
        timeline.reset()
        monkeypatch.delenv('SKYT_DEBUG', raising=False)
        with timeline.Event('off-event'):
            pass
        assert not timeline._events
        monkeypatch.setenv('SKYT_DEBUG', '1')
        with timeline.Event('on-event'):
            pass
        assert [e['name'] for e in timeline._events] == \
            ['on-event', 'on-event']        # B + E pair
        monkeypatch.delenv('SKYT_DEBUG', raising=False)
        with timeline.Event('off-again'):
            pass
        assert len(timeline._events) == 2   # no new events
        timeline.reset()
        assert not timeline._events
