"""Host-runtime tests: job queue, log runner, and a REAL 2-host gang.

The gang test spawns two agent daemons (rank 0 = head with the HTTP
coordinator, rank 1 = worker) as subprocesses with separate per-host homes
on 127.0.0.1 — the offline multi-host harness the reference lacks
(SURVEY.md §4 implication).
"""
import json
import os
import socket
import subprocess
import sys
import time

import pytest
import requests

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


@pytest.fixture()
def agent_env(tmp_path, monkeypatch):
    """Point job_lib at a tmp agent home and reset its cached DB."""
    home = tmp_path / 'host0'
    home.mkdir()
    monkeypatch.setenv('SKYT_AGENT_HOME', str(home))
    from skypilot_tpu.runtime import job_lib
    job_lib.reset_db_for_testing()
    yield home
    job_lib.reset_db_for_testing()


class TestJobLib:

    def test_add_and_status_lifecycle(self, agent_env):
        from skypilot_tpu.runtime import job_lib
        job_id = job_lib.add_job('train', {'run': 'echo hi', 'num_nodes': 2})
        job = job_lib.get_job(job_id)
        assert job['status'] == job_lib.JobStatus.PENDING
        assert len(job_lib.gang_records(job_id)) == 2
        job_lib.set_status(job_id, job_lib.JobStatus.RUNNING)
        assert job_lib.get_job(job_id)['start_at'] is not None
        job_lib.set_status(job_id, job_lib.JobStatus.SUCCEEDED)
        job = job_lib.get_job(job_id)
        assert job['end_at'] is not None
        assert job['status'].is_terminal()
        assert job_lib.is_cluster_idle()

    def test_fifo_accelerator_exclusive(self, agent_env):
        from skypilot_tpu.runtime import job_lib
        sched = job_lib.FIFOScheduler()
        j1 = job_lib.add_job('a', {'run': 'x', 'accelerators': 'tpu-v5e-8'})
        j2 = job_lib.add_job('b', {'run': 'y', 'accelerators': 'tpu-v5e-8'})
        assert sched.schedule_step() == j1
        job_lib.set_status(j1, job_lib.JobStatus.RUNNING)
        # Accelerator job running -> nothing else schedulable.
        assert sched.schedule_step() is None
        job_lib.set_status(j1, job_lib.JobStatus.SUCCEEDED)
        assert sched.schedule_step() == j2

    def test_cpu_jobs_concurrent(self, agent_env):
        from skypilot_tpu.runtime import job_lib
        sched = job_lib.FIFOScheduler()
        j1 = job_lib.add_job('a', {'run': 'x'})
        j2 = job_lib.add_job('b', {'run': 'y'})
        assert sched.schedule_step() == j1
        job_lib.set_status(j1, job_lib.JobStatus.RUNNING)
        assert sched.schedule_step() == j2

    def test_gang_aggregation(self, agent_env):
        from skypilot_tpu.runtime import job_lib
        job_id = job_lib.add_job('g', {'run': 'x', 'num_nodes': 2})
        job_lib.gang_mark(job_id, 0, 'DONE', 0)
        assert not job_lib.gang_all_done(job_id)
        job_lib.gang_mark(job_id, 1, 'DONE', 1)
        assert job_lib.gang_all_done(job_id)
        assert job_lib.gang_any_failed(job_id)


class TestLogLib:

    def test_run_with_log(self, tmp_path):
        from skypilot_tpu.runtime import log_lib
        log = tmp_path / 'x.log'
        rc, pid = log_lib.run_with_log('echo out; echo err >&2', str(log))
        assert rc == 0 and pid > 0
        content = log.read_text()
        assert 'out' in content and 'err' in content

    def test_task_script_env(self, tmp_path):
        from skypilot_tpu.runtime import log_lib
        script = log_lib.make_task_bash_script(
            'echo "rank=$SKYT_NODE_RANK"', {'SKYT_NODE_RANK': '3'})
        log = tmp_path / 'y.log'
        rc, _ = log_lib.run_with_log(['bash', script], str(log))
        assert rc == 0
        assert 'rank=3' in log.read_text()
        os.unlink(script)

    def test_tail_follow_drains(self, tmp_path):
        from skypilot_tpu.runtime import log_lib
        log = tmp_path / 'z.log'
        log.write_text('line1\n')
        done = {'v': False}
        lines = []
        import threading

        def _tail():
            for line in log_lib.tail_logs(str(log), follow=True,
                                          job_done=lambda: done['v'],
                                          poll_interval=0.05):
                lines.append(line)

        t = threading.Thread(target=_tail)
        t.start()
        time.sleep(0.2)
        with open(log, 'a') as f:
            f.write('line2\n')
        time.sleep(0.2)
        done['v'] = True
        t.join(timeout=5)
        assert ''.join(lines) == 'line1\nline2\n'


class TestGangEnv:

    def test_env_contract(self):
        from skypilot_tpu.runtime import gang
        env = gang.job_env_vars(job_id=7, rank=1,
                                ips=['10.0.0.1', '10.0.0.2'],
                                cluster_name='c1', task_name='t',
                                accelerators_per_node=4)
        assert env['SKYT_NUM_NODES'] == '2'
        assert env['SKYT_NODE_RANK'] == '1'
        assert env['SKYT_NODE_IPS'] == '10.0.0.1\n10.0.0.2'
        assert env['SKYPILOT_NUM_GPUS_PER_NODE'] == '4'
        assert env['JAX_COORDINATOR_ADDRESS'] == '10.0.0.1:8476'
        assert env['JAX_PROCESS_ID'] == '1'
        assert env['SKYT_TASK_ID'].endswith('_c1_t-7')

    def test_single_node_no_jax_coordinator(self):
        from skypilot_tpu.runtime import gang
        env = gang.job_env_vars(job_id=1, rank=0, ips=['10.0.0.1'],
                                cluster_name='c1')
        assert 'JAX_COORDINATOR_ADDRESS' not in env

    def test_user_env_cannot_shadow_contract(self):
        from skypilot_tpu.runtime import gang
        env = gang.job_env_vars(job_id=1, rank=0,
                                ips=['10.0.0.1', '10.0.0.2'],
                                cluster_name='c1',
                                user_envs={'SKYT_NODE_RANK': '99',
                                           'MY_VAR': 'ok'})
        assert env['SKYT_NODE_RANK'] == '0'
        assert env['MY_VAR'] == 'ok'


# --------------------------------------------------------------------------
# Full gang integration: two real agent processes.
# --------------------------------------------------------------------------
class GangCluster:
    """Spawn N agent daemons with per-host homes on 127.0.0.1."""

    def __init__(self, base_dir: str, num_nodes: int = 2) -> None:
        self.base = base_dir
        self.num_nodes = num_nodes
        self.port = _free_port()
        self.procs = []
        self.homes = []
        ips = ['127.0.0.1'] * num_nodes
        for rank in range(num_nodes):
            home = os.path.join(base_dir, f'host{rank}')
            os.makedirs(os.path.join(home, '.skyt'), exist_ok=True)
            cfg = {
                'cluster_name': 'testgang',
                'num_nodes': num_nodes,
                'rank': rank,
                'ips': ips,
                'head_ip': '127.0.0.1',
                'head_port': self.port,
                'accelerators_per_node': 0,
                'cloud': 'local',
            }
            cfg_path = os.path.join(home, '.skyt', 'agent.json')
            with open(cfg_path, 'w') as f:
                json.dump(cfg, f)
            self.homes.append(home)
            env = dict(os.environ)
            env['SKYT_AGENT_HOME'] = home
            env['PYTHONPATH'] = REPO_ROOT
            env.pop('JAX_PLATFORMS', None)
            proc = subprocess.Popen(
                [sys.executable, '-m', 'skypilot_tpu.runtime.agent',
                 '--config', cfg_path, '--foreground'],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT)
            self.procs.append(proc)

    @property
    def url(self) -> str:
        return f'http://127.0.0.1:{self.port}'

    def wait_ready(self, timeout: float = 20) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if requests.get(self.url + '/health', timeout=2).ok:
                    return
            except requests.RequestException:
                pass
            time.sleep(0.2)
        raise TimeoutError('head agent did not come up')

    def submit(self, spec: dict) -> int:
        resp = requests.post(self.url + '/jobs/submit', json={'spec': spec},
                             timeout=10)
        resp.raise_for_status()
        return resp.json()['job_id']

    def job(self, job_id: int) -> dict:
        resp = requests.get(self.url + f'/jobs/{job_id}', timeout=10)
        resp.raise_for_status()
        return resp.json()

    def wait_job(self, job_id: int, timeout: float = 60) -> dict:
        from skypilot_tpu.runtime import job_lib
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = self.job(job_id)
            if job_lib.JobStatus(job['status']).is_terminal():
                return job
            time.sleep(0.3)
        raise TimeoutError(f'job {job_id} did not finish: {self.job(job_id)}')

    def shutdown(self) -> None:
        for proc in self.procs:
            proc.terminate()
        for proc in self.procs:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


@pytest.fixture()
def gang_cluster(tmp_path):
    cluster = GangCluster(str(tmp_path), num_nodes=2)
    try:
        cluster.wait_ready()
        yield cluster
    finally:
        cluster.shutdown()


@pytest.mark.integration
class TestGangIntegration:

    def test_two_node_gang_env_and_logs(self, gang_cluster):
        c = gang_cluster
        job_id = c.submit({
            'name': 'envcheck',
            'run': 'echo "rank=$SKYT_NODE_RANK nodes=$SKYT_NUM_NODES '
                   'jaxid=$JAX_PROCESS_ID"',
            'num_nodes': 2,
        })
        job = c.wait_job(job_id)
        assert job['status'] == 'SUCCEEDED', job
        for rank in (0, 1):
            log = os.path.join(c.homes[rank], '.skyt', 'logs', str(job_id),
                               f'rank-{rank}.log')
            content = open(log).read()
            assert f'rank={rank} nodes=2 jaxid={rank}' in content

    def test_setup_failure_marks_failed_setup(self, gang_cluster):
        c = gang_cluster
        job_id = c.submit({'name': 'bad', 'setup': 'exit 42',
                           'run': 'echo never', 'num_nodes': 2})
        job = c.wait_job(job_id)
        assert job['status'] == 'FAILED_SETUP'

    def test_one_rank_fails_job_fails(self, gang_cluster):
        c = gang_cluster
        job_id = c.submit({
            'name': 'halffail',
            'run': 'if [ "$SKYT_NODE_RANK" = "1" ]; then exit 3; fi',
            'num_nodes': 2,
        })
        job = c.wait_job(job_id)
        assert job['status'] == 'FAILED'

    def test_cancel_kills_running_job(self, gang_cluster):
        c = gang_cluster
        job_id = c.submit({'name': 'sleeper', 'run': 'sleep 300',
                           'num_nodes': 2})
        # Wait until RUNNING.
        deadline = time.time() + 30
        while time.time() < deadline:
            if c.job(job_id)['status'] == 'RUNNING':
                break
            time.sleep(0.2)
        assert c.job(job_id)['status'] == 'RUNNING'
        resp = requests.post(c.url + f'/jobs/{job_id}/cancel', json={},
                             timeout=10)
        assert resp.json()['cancelled']
        job = c.wait_job(job_id, timeout=30)
        assert job['status'] == 'CANCELLED'

    def test_fifo_second_job_runs_after_first(self, gang_cluster):
        c = gang_cluster
        j1 = c.submit({'name': 'first', 'run': 'sleep 1',
                       'accelerators': 'tpu-v5e-8', 'num_nodes': 2})
        j2 = c.submit({'name': 'second', 'run': 'echo two',
                       'accelerators': 'tpu-v5e-8', 'num_nodes': 2})
        job2 = c.wait_job(j2, timeout=90)
        job1 = c.job(j1)
        assert job1['status'] == 'SUCCEEDED'
        assert job2['status'] == 'SUCCEEDED'
        # Second started only after first ended.
        assert job2['start_at'] >= job1['end_at'] - 1.0
