"""QoS admission control (serve/qos.py + its wiring, docs/qos.md):

Fast tier — pure scheduling/parsing logic, no model:
  * header contract: X-Priority / X-Tenant / OpenAI service_tier
    parsing, malformed forms rejected;
  * token-bucket refill determinism under a seeded clock;
  * DRR fair queue: strict class order, FIFO within a flow, fairness
    under a single-tenant batch flood, aging prevents starvation;
  * ClassedRequestQueue reorder/apply_order semantics;
  * overload ladder levels + hysteresis, shed/degrade decisions, and
    the qos.shed / qos.throttle fault points;
  * autoscaler satellites: timestamp-buffer cap + QoS-aware targets;
  * lint rule: direct _waiting.put( outside the admission path flags.

Heavy tier — the real engine/server with SKYT_QOS=1:
  * priority ordering through engine.submit + per-class metrics;
  * server 400s on malformed headers, 429 + Retry-After on forced
    sheds, degrade clamps max_tokens;
  * LB 503 carries Retry-After (satellite).
"""
import os
import socket
import threading
import time

import pytest

from skypilot_tpu.serve import qos
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ========================================================= header contract
def test_parse_priority():
    assert qos.parse_priority(None) == 'standard'
    assert qos.parse_priority('') == 'standard'
    assert qos.parse_priority('interactive') == 'interactive'
    assert qos.parse_priority(' Batch ') == 'batch'
    with pytest.raises(ValueError, match='urgent'):
        qos.parse_priority('urgent')


def test_parse_tenant():
    assert qos.parse_tenant(None) == 'default'
    assert qos.parse_tenant('team-a_1.prod') == 'team-a_1.prod'
    with pytest.raises(ValueError):
        qos.parse_tenant('bad tenant!')
    with pytest.raises(ValueError):
        qos.parse_tenant('x' * 65)


def test_map_service_tier():
    assert qos.map_service_tier(None) is None
    assert qos.map_service_tier('priority') == 'interactive'
    assert qos.map_service_tier('default') == 'standard'
    assert qos.map_service_tier('flex') == 'batch'
    with pytest.raises(ValueError, match='gold'):
        qos.map_service_tier('gold')


def test_retry_after_header_rounds_up():
    assert qos.retry_after_header(0.2) == '1'
    assert qos.retry_after_header(1.0) == '1'
    assert qos.retry_after_header(1.2) == '2'


# ============================================================ token bucket
def test_token_bucket_refill_determinism():
    """Same seeded clock trajectory => identical grant pattern, and
    the refill math is exact (no wall-clock dependence)."""
    def pattern():
        clock = FakeClock()
        tb = qos.TokenBucket(rate=2.0, burst=4.0, clock=clock)
        grants = []
        for step in range(20):
            ok, retry = tb.try_take()
            grants.append((ok, round(retry, 6)))
            clock.advance(0.25 if step % 3 else 0.0)
        return grants
    a, b = pattern(), pattern()
    assert a == b
    assert a[0] == (True, 0.0)
    assert any(not ok for ok, _ in a)          # bucket does run dry


def test_token_bucket_retry_after_is_exact():
    clock = FakeClock()
    tb = qos.TokenBucket(rate=2.0, burst=1.0, clock=clock)
    assert tb.try_take() == (True, 0.0)
    ok, retry = tb.try_take()
    assert not ok and retry == pytest.approx(0.5)   # 1 token / 2 per s
    clock.advance(0.5)
    assert tb.try_take() == (True, 0.0)


def test_tenant_rate_limiter_isolates_tenants():
    clock = FakeClock()
    lim = qos.TenantRateLimiter(rate=1.0, burst=1.0, clock=clock)
    assert lim.try_take('a')[0]
    assert not lim.try_take('a')[0]        # a's bucket is dry
    assert lim.try_take('b')[0]            # b unaffected
    # rate <= 0 disables limiting
    off = qos.TenantRateLimiter(rate=0.0, burst=0.0, clock=clock)
    assert all(off.try_take('x')[0] for _ in range(100))


def test_tenant_rate_limiter_bounded_tenants():
    clock = FakeClock()
    lim = qos.TenantRateLimiter(rate=1.0, burst=1.0, max_tenants=4,
                                clock=clock)
    for i in range(100):
        lim.try_take(f't{i}')
    assert len(lim._buckets) <= 4   # pylint: disable=protected-access


# ========================================================== DRR fair queue
def test_fairqueue_strict_class_order():
    clock = FakeClock()
    fq = qos.FairQueue(quantum=10, aging_s=1000, clock=clock)
    fq.push('b1', 'batch', cost=1)
    fq.push('s1', 'standard', cost=1)
    fq.push('i1', 'interactive', cost=1)
    fq.push('i2', 'interactive', cost=1)
    assert fq.drain() == ['i1', 'i2', 's1', 'b1']


def test_fairqueue_fifo_within_flow():
    fq = qos.FairQueue(quantum=10, aging_s=1000, clock=FakeClock())
    for i in range(8):
        fq.push(i, 'standard', 'tA', cost=3)
    assert fq.drain() == list(range(8))


def test_fairqueue_drr_fairness_under_batch_flood():
    """One tenant floods the batch class; a second tenant's handful of
    batch requests must be served interleaved (within a couple of DRR
    rounds), not after the entire flood."""
    fq = qos.FairQueue(quantum=10, aging_s=1000, clock=FakeClock())
    for i in range(50):
        fq.push(('flood', i), 'batch', 'flooder', cost=10)
    for i in range(5):
        fq.push(('small', i), 'batch', 'small-tenant', cost=10)
    order = fq.drain()
    positions = [order.index(('small', i)) for i in range(5)]
    # Equal costs and weights => near-perfect alternation: the small
    # tenant's 5 requests all land in the first ~12 pops.
    assert max(positions) <= 12, positions
    # And within the small tenant, FIFO survives.
    assert positions == sorted(positions)


def test_fairqueue_weighted_drr():
    """Unequal costs: the DRR quantum meters out service by COST, so a
    tenant with expensive requests gets fewer of them per round."""
    fq = qos.FairQueue(quantum=10, aging_s=1000, clock=FakeClock())
    for i in range(6):
        fq.push(('cheap', i), 'batch', 'cheap', cost=5)
    for i in range(6):
        fq.push(('fat', i), 'batch', 'fat', cost=20)
    order = fq.drain()
    # After 12 pops: cheap got ~2x the requests of fat in any prefix
    # covering whole rounds.
    first8 = order[:8]
    n_cheap = sum(1 for x in first8 if x[0] == 'cheap')
    n_fat = sum(1 for x in first8 if x[0] == 'fat')
    assert n_cheap > n_fat, order


def test_fairqueue_aging_prevents_starvation():
    """A batch request older than 2*aging_s outranks fresh interactive
    traffic (its band descends below rank 0)."""
    clock = FakeClock(1000.0)
    fq = qos.FairQueue(quantum=10, aging_s=10, clock=clock)
    fq.push('old-batch', 'batch', cost=1, t=1000.0 - 25)   # aged 2 bands
    fq.push('fresh-i', 'interactive', cost=1, t=1000.0)
    assert fq.pop() == 'old-batch'
    # Without aging the same shape serves interactive first.
    fq2 = qos.FairQueue(quantum=10, aging_s=10, clock=clock)
    fq2.push('batch', 'batch', cost=1, t=1000.0 - 5)       # not aged yet
    fq2.push('fresh-i', 'interactive', cost=1, t=1000.0)
    assert fq2.pop() == 'fresh-i'


def test_fairqueue_depths():
    fq = qos.FairQueue(clock=FakeClock())
    fq.push('a', 'batch')
    fq.push('b', 'batch')
    fq.push('c', 'interactive')
    assert fq.depths() == {'interactive': 1, 'standard': 0, 'batch': 2}
    assert len(fq) == 3


# ================================================== ClassedRequestQueue
class _Item:
    def __init__(self, seq, cls='standard', tenant='default',
                 cost=1.0, t=0.0):
        self.seq = seq
        self.cls = cls
        self.tenant = tenant
        self.cost = cost
        self.t = t

    def __repr__(self):
        return f'<{self.seq}:{self.cls}>'


def _crq(clock=None, **kw):
    clock = clock or FakeClock()
    return qos.ClassedRequestQueue(
        meta=lambda it: qos.RequestMeta(
            cls=it.cls, tenant=it.tenant, cost=it.cost, seq=it.seq,
            enq_t=it.t),
        quantum=10, aging_s=1000, debt_halflife_s=30, clock=clock), \
        clock


def test_classed_queue_reorder_and_pop():
    q, clock = _crq()
    for i in range(3):
        q.put(_Item(i, 'batch'))
    q.put(_Item(3, 'interactive'))
    q.put(_Item(4, 'standard'))
    order, changed = q.reorder(clock())
    assert changed
    assert order == [3, 4, 0, 1, 2]
    assert q.get_nowait().seq == 3      # pops follow the schedule
    assert q.get_nowait().seq == 4
    # A second reorder with no new arrivals: already in order.
    order2, changed2 = q.reorder(clock())
    assert order2 == [0, 1, 2] and not changed2


def test_classed_queue_apply_order():
    q, _clock = _crq()
    for i in range(4):
        q.put(_Item(i))
    q.apply_order([2, 0, 3, 1])
    assert [q.get_nowait().seq for _ in range(4)] == [2, 0, 3, 1]


def test_classed_queue_debt_biases_next_round():
    """A tenant whose burst was just served starts the next round
    indebted: a fresh arrival from a peer tenant schedules ahead of
    the indebted tenant's backlog."""
    q, clock = _crq()
    for i in range(6):
        q.put(_Item(i, 'batch', 'greedy', cost=10))
    q.reorder(clock())
    for _ in range(4):                      # serve greedy's head burst
        q.get_nowait()
    q.put(_Item(100, 'batch', 'polite', cost=10))
    order, _ = q.reorder(clock())
    assert order[0] == 100, order           # polite jumps the backlog


def test_classed_queue_batch_bucket_prefix_preserved():
    """Within a class the schedule is arrival-ordered per tenant, so a
    same-bucket FIFO prefix (what batched admission collects) never
    straddles a class boundary: all interactive items sort strictly
    before all batch items."""
    q, clock = _crq()
    for i in range(4):
        q.put(_Item(i, 'batch'))
    for i in range(4, 8):
        q.put(_Item(i, 'interactive'))
    order, _ = q.reorder(clock())
    assert order == [4, 5, 6, 7, 0, 1, 2, 3]


# ========================================================= overload ladder
def _controller(sig, clock=None, **env):
    clock = clock or FakeClock()
    defaults = {'SKYT_QOS_QUEUE_DEGRADE': '4',
                'SKYT_QOS_QUEUE_SHED': '8',
                'SKYT_QOS_HOLD_S': '2', 'SKYT_QOS_REFRESH_S': '0',
                'SKYT_QOS_TTFT_SLO_MS': '500'}
    defaults.update({k: str(v) for k, v in env.items()})
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        ctl = qos.OverloadController(sig, clock=clock)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return ctl, clock


def test_overload_levels_from_queue_depth():
    state = {'queue_depth': 0.0, 'num_slots': 2.0}
    ctl, clock = _controller(lambda: state)
    assert ctl.level() == 0
    state['queue_depth'] = 9.0        # ratio 4.5 >= degrade(4)
    clock.advance(1)
    assert ctl.level() == 1
    state['queue_depth'] = 17.0       # ratio 8.5 >= shed(8)
    clock.advance(1)
    assert ctl.level() == 2
    state['queue_depth'] = 33.0       # ratio 16.5 >= 2*shed
    clock.advance(1)
    assert ctl.level() == 3


def test_overload_kv_and_ttft_signals():
    state = {'queue_depth': 0.0, 'num_slots': 8.0, 'kv_util': 0.95}
    ctl, clock = _controller(lambda: state)
    assert ctl.level() == 1            # kv >= degrade(0.90)
    state['kv_util'] = 0.99
    clock.advance(1)
    assert ctl.level() == 2            # kv >= shed(0.97)
    state['kv_util'] = 0.0
    state['ttft_p95_s'] = 1.2          # > 2 * 500ms SLO
    clock.advance(10)                  # past the de-escalation hold
    assert ctl.level() == 2


def test_overload_hysteresis_holds_before_deescalating():
    state = {'queue_depth': 20.0, 'num_slots': 2.0}
    ctl, clock = _controller(lambda: state)
    assert ctl.level() == 2
    state['queue_depth'] = 0.0
    clock.advance(0.5)
    assert ctl.level() == 2            # still inside the hold window
    clock.advance(3.0)
    assert ctl.level() == 0            # held below long enough


def test_overload_retry_after_scales_with_level():
    ctl, _ = _controller(lambda: {})
    assert ctl.retry_after(1) == pytest.approx(1.0)
    assert ctl.retry_after(3) == pytest.approx(4.0)
    assert ctl.retry_after(30) == 30.0          # capped


# ========================================================= ServerQoS gate
def _server_qos(sig, clock=None, **env):
    clock = clock or FakeClock()
    defaults = {'SKYT_QOS_QUEUE_DEGRADE': '4',
                'SKYT_QOS_QUEUE_SHED': '8',
                'SKYT_QOS_HOLD_S': '2', 'SKYT_QOS_REFRESH_S': '0',
                'SKYT_QOS_DEGRADE_MAX_TOKENS': '32',
                'SKYT_QOS_TENANT_RPS': '0'}
    defaults.update({k: str(v) for k, v in env.items()})
    old = {k: os.environ.get(k) for k in defaults}
    os.environ.update(defaults)
    try:
        sq = qos.ServerQoS(sig, registry=metrics_lib.MetricsRegistry(),
                           clock=clock)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return sq, clock


def test_shed_ladder_lowest_class_first():
    state = {'queue_depth': 17.0, 'num_slots': 2.0}   # level 2
    sq, _ = _server_qos(lambda: state)
    assert sq.admit('batch', 't').action == 'shed'
    d = sq.admit('standard', 't', max_new_tokens=128)
    assert d.action == 'degrade' and d.max_new_tokens == 32
    assert sq.admit('interactive', 't').action == 'admit'
    state['queue_depth'] = 40.0                        # level 3
    sq2, _ = _server_qos(lambda: state)
    assert sq2.admit('standard', 't').action == 'shed'
    assert sq2.admit('batch', 't').action == 'shed'
    # Interactive is NEVER shed by the overload controller.
    assert sq2.admit('interactive', 't').action == 'admit'


def test_degrade_before_shed_for_batch():
    state = {'queue_depth': 9.0, 'num_slots': 2.0}     # level 1
    sq, _ = _server_qos(lambda: state)
    d = sq.admit('batch', 't', max_new_tokens=500)
    assert d.action == 'degrade' and d.max_new_tokens == 32
    # Small batch requests under the clamp are admitted untouched.
    assert sq.admit('batch', 't', max_new_tokens=8).action == 'admit'
    assert sq.admit('standard', 't',
                    max_new_tokens=500).action == 'admit'


def test_shed_retry_after_positive():
    state = {'queue_depth': 17.0, 'num_slots': 2.0}
    sq, _ = _server_qos(lambda: state)
    d = sq.admit('batch', 't')
    assert d.action == 'shed' and d.retry_after > 0


def test_throttle_via_token_bucket():
    sq, _ = _server_qos(lambda: {}, SKYT_QOS_TENANT_RPS='1',
                        SKYT_QOS_TENANT_BURST='2')
    actions = [sq.admit('interactive', 'spammer').action
               for _ in range(4)]
    assert actions[:2] == ['admit', 'admit']
    assert actions[2] == 'throttle'
    # Another tenant is unaffected.
    assert sq.admit('interactive', 'quiet').action == 'admit'


def test_qos_fault_points_force_paths():
    """Chaos hook: armed qos.shed / qos.throttle rules force the
    decision regardless of load, honoring where= class filters."""
    sq, _ = _server_qos(lambda: {})
    faults.configure('qos.shed=error,where=cls:batch')
    assert sq.admit('batch', 't').action == 'shed'
    assert sq.admit('interactive', 't').action == 'admit'
    faults.configure('qos.throttle=error,where=cls:interactive')
    assert sq.admit('interactive', 't').action == 'throttle'
    assert faults.fired_counts()[('qos.throttle', 'error')] == 1


def test_shed_metrics_count_by_class():
    state = {'queue_depth': 17.0, 'num_slots': 2.0}
    reg = metrics_lib.MetricsRegistry()
    os.environ.update({'SKYT_QOS_QUEUE_SHED': '8',
                       'SKYT_QOS_REFRESH_S': '0',
                       'SKYT_QOS_HOLD_S': '2'})
    try:
        sq = qos.ServerQoS(lambda: state, registry=reg,
                           clock=FakeClock())
        sq.admit('batch', 't')
        sq.admit('interactive', 't')
    finally:
        for k in ('SKYT_QOS_QUEUE_SHED', 'SKYT_QOS_REFRESH_S',
                  'SKYT_QOS_HOLD_S'):
            os.environ.pop(k, None)
    shed = reg.counter('skyt_qos_shed_total', '', ('class', 'model'))
    assert shed.value('batch', '') == 1
    assert shed.value('interactive', '') == 0


def test_snapshot_shape():
    sq, _ = _server_qos(lambda: {'queue_depth': 17, 'num_slots': 2})
    snap = sq.snapshot({'interactive': 0, 'standard': 1, 'batch': 16})
    assert snap['level'] == 2
    assert 0 <= snap['pressure'] <= 1
    assert snap['retry_after_s'] > 0
    assert snap['classes']['batch'] == 16


def test_shed_avoid_classes():
    assert qos.shed_avoid_classes(0) == ()
    assert qos.shed_avoid_classes(2) == ('batch',)
    assert set(qos.shed_avoid_classes(3)) == {'standard', 'batch'}


# ======================================================= autoscaler plane
def test_autoscaler_timestamp_buffer_cap(monkeypatch):
    """Satellite: the request-timestamp buffer is bounded drop-oldest
    with a drop counter (mirrors the PR 4 LB sync-buffer fix)."""
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import service_spec as spec_lib
    monkeypatch.setenv('SKYT_AUTOSCALER_MAX_TIMESTAMPS', '100')
    reg = metrics_lib.MetricsRegistry()
    spec = spec_lib.ServiceSpec(readiness_path='/health',
                                min_replicas=1)
    a = autoscalers.RequestRateAutoscaler(spec, metrics_registry=reg)
    now = time.time()
    a.collect_request_timestamps([now] * 250)
    assert len(a.request_timestamps) == 100
    dropped = reg.counter(
        'skyt_autoscaler_dropped_timestamps_total', '')
    assert dropped.value() == 150


def test_qos_autoscaler_weighted_demand_and_sheds(monkeypatch):
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import service_spec as spec_lib
    spec = spec_lib.ServiceSpec(readiness_path='/health',
                                min_replicas=1, max_replicas=10,
                                target_qps_per_replica=1.0)
    a = autoscalers.QoSAwareAutoscaler(
        spec, metrics_registry=metrics_lib.MetricsRegistry())
    now = time.time()
    # 120 interactive + 240 batch over the 60s window. Weighted QPS =
    # 1.0*2 + 0.25*4 = 3 -> 3 replicas.
    a.collect_qos([[now, 'interactive']] * 120 +
                  [[now, 'batch']] * 240, [])
    assert a._raw_target() == 3   # pylint: disable=protected-access
    # 60 observed sheds (1 shed QPS): +1 replica on top.
    a.collect_qos([], [[now, 'batch']] * 60)
    assert a._raw_target() == 4   # pylint: disable=protected-access


def test_qos_autoscaler_falls_back_to_raw_rate():
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import service_spec as spec_lib
    spec = spec_lib.ServiceSpec(readiness_path='/health',
                                min_replicas=1, max_replicas=10,
                                target_qps_per_replica=1.0)
    a = autoscalers.QoSAwareAutoscaler(
        spec, metrics_registry=metrics_lib.MetricsRegistry())
    now = time.time()
    a.collect_request_timestamps([now] * 120)   # 2 QPS, no class data
    assert a._raw_target() == 2   # pylint: disable=protected-access


def test_pick_autoscaler_cls(monkeypatch):
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import service_spec as spec_lib
    spec = spec_lib.ServiceSpec(readiness_path='/health',
                                min_replicas=1)
    monkeypatch.delenv('SKYT_QOS', raising=False)
    assert autoscalers.pick_autoscaler_cls(spec) is \
        autoscalers.RequestRateAutoscaler
    monkeypatch.setenv('SKYT_QOS', '1')
    assert autoscalers.pick_autoscaler_cls(spec) is \
        autoscalers.QoSAwareAutoscaler
    spec_fb = spec_lib.ServiceSpec(readiness_path='/health',
                                   min_replicas=1,
                                   base_ondemand_fallback_replicas=1)
    assert autoscalers.pick_autoscaler_cls(spec_fb) is \
        autoscalers.FallbackRequestRateAutoscaler


# ============================================================= lint rule
def test_lint_forbids_direct_waiting_put(tmp_path):
    """tools/lint.py flags new direct _waiting.put( callsites in
    infer/ outside the sanctioned admission path (satellite)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), 'tools'))
    import lint   # noqa: E402
    d = tmp_path / 'skypilot_tpu' / 'infer'
    d.mkdir(parents=True)
    bad = d / 'sneaky.py'
    bad.write_text('def f(eng, req):\n'
                   '    eng._waiting.put(req)\n')
    issues = lint.check_file(bad)
    assert any('_waiting.put' in i for i in issues), issues
    ok = d / 'fine.py'
    ok.write_text('def f(eng, req):\n'
                  '    eng._waiting.put(req)   # qos-admission\n')
    assert not lint.check_file(ok)
    # Outside infer/ the rule does not apply.
    d2 = tmp_path / 'skypilot_tpu' / 'serve'
    d2.mkdir(parents=True)
    other = d2 / 'x.py'
    other.write_text('def f(eng, req):\n'
                     '    eng._waiting.put(req)\n')
    assert not lint.check_file(other)


# ============================================= engine + server integration
def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _run_app_bg(app, port) -> None:
    from aiohttp import web
    threading.Thread(target=lambda: web.run_app(
        app, port=port, print=None, handle_signals=False),
        daemon=True).start()


def _wait_http(url: str, timeout: float = 60) -> None:
    import requests
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if requests.get(url, timeout=2).status_code == 200:
                return
        except requests.RequestException:
            pass
        time.sleep(0.2)
    raise AssertionError(f'{url} never became healthy')


def _debug_engine(reg, num_slots=2):
    import dataclasses
    import jax
    import jax.numpy as jnp
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.models import llama
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    return engine_lib.InferenceEngine(model, params,
                                      num_slots=num_slots,
                                      max_seq_len=64, decode_chunk=4,
                                      prefill_buckets=[16],
                                      metrics_registry=reg)


@pytest.mark.heavy
def test_sampling_params_priority_validation():
    from skypilot_tpu.infer import engine as engine_lib
    engine_lib.SamplingParams(priority='batch',
                              tenant='team-a').validate()
    with pytest.raises(ValueError, match='priority'):
        engine_lib.SamplingParams(priority='vip').validate()
    with pytest.raises(ValueError, match='tenant'):
        engine_lib.SamplingParams(tenant=7).validate()


@pytest.mark.heavy
@pytest.mark.integration
def test_engine_priority_ordering_and_metrics(monkeypatch):
    """With SKYT_QOS=1 the engine schedules interactive ahead of a
    queued batch backlog (observable via first_token order), records
    per-class queue-wait/TTFT histograms, and exposes per-class
    depths/signals for the server layers."""
    monkeypatch.setenv('SKYT_QOS', '1')
    from skypilot_tpu.infer import engine as engine_lib
    reg = metrics_lib.MetricsRegistry()
    eng = _debug_engine(reg)
    # All batch requests first, then one interactive: with FIFO the
    # interactive one would be admitted LAST.
    batch = [eng.submit([1, 2, 3], engine_lib.SamplingParams(
        max_new_tokens=6, priority='batch', tenant='flooder'))
        for _ in range(6)]
    rid_i, q_i = eng.submit([4, 5, 6], engine_lib.SamplingParams(
        max_new_tokens=6, priority='interactive', tenant='user'))
    eng.start()
    try:
        queues = [q for _, q in batch] + [q_i]
        for q in queues:
            while q.get(timeout=120) is not None:
                pass
    finally:
        eng.stop()
    t_i = eng.request_trace(rid_i)['first_token']
    batch_firsts = sorted(
        eng.request_trace(rid)['first_token'] for rid, _ in batch)
    # The interactive request got its first token before at least the
    # back half of the batch backlog (it may share the very first
    # admission round with batch head(s) already popped).
    assert t_i < batch_firsts[2], (t_i, batch_firsts)
    ttft = reg.histogram('skyt_qos_ttft_seconds', '', ('class',))
    samples = {tuple(s['labels'].values()): s
               for s in ttft.sample_dicts()}
    assert ('interactive',) in samples and ('batch',) in samples
    assert eng.qos_depths() == {'interactive': 0, 'standard': 0,
                                'batch': 0}
    sig = eng.qos_signals()
    assert sig['num_slots'] == 2.0 and 'ttft_p95_s' in sig


@pytest.mark.heavy
@pytest.mark.integration
def test_engine_reserved_slots_gate_batch(monkeypatch):
    """SKYT_QOS_RESERVE_SLOTS=1: batch admissions leave one slot free
    for interactive arrivals."""
    monkeypatch.setenv('SKYT_QOS', '1')
    monkeypatch.setenv('SKYT_QOS_RESERVE_SLOTS', '1')
    from skypilot_tpu.infer import engine as engine_lib
    reg = metrics_lib.MetricsRegistry()
    eng = _debug_engine(reg, num_slots=2)
    eng.start()
    try:
        # Long-running batch requests: only ONE may occupy a slot.
        subs = [eng.submit([1, 2, 3], engine_lib.SamplingParams(
            max_new_tokens=40, priority='batch'))
            for _ in range(3)]
        deadline = time.time() + 60
        while time.time() < deadline and \
                eng.stats()['active_slots'] == 0:
            time.sleep(0.02)
        time.sleep(0.3)     # give the loop a chance to (wrongly) seat 2
        assert eng.stats()['active_slots'] == 1
        # An interactive request takes the reserved slot immediately.
        rid, q = eng.submit([7, 8, 9], engine_lib.SamplingParams(
            max_new_tokens=2, priority='interactive'))
        while q.get(timeout=60) is not None:
            pass
        assert eng.request_trace(rid)['status'] == 'done'
        for _, qb in subs:
            while qb.get(timeout=120) is not None:
                pass
    finally:
        eng.stop()


@pytest.mark.heavy
@pytest.mark.integration
def test_server_qos_headers_and_forced_shed(monkeypatch):
    """HTTP surface: malformed X-Priority/X-Tenant 400 naming the
    offender (QoS on or off); a forced qos.shed returns 429 +
    Retry-After and never reaches the engine; degrade clamps
    max_tokens; /stats exposes the qos snapshot."""
    import requests
    from skypilot_tpu.infer import server as server_lib
    monkeypatch.setenv('SKYT_QOS', '1')
    monkeypatch.setenv('SKYT_QOS_TTFT_SLO_MS', '0')
    reg = metrics_lib.MetricsRegistry()
    eng = _debug_engine(reg)
    eng.start()
    srv = server_lib.InferenceServer(eng)
    port = _free_port()
    _run_app_bg(srv.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    _wait_http(base + '/health', timeout=120)
    try:
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2], 'max_tokens': 2},
                          headers={'X-Priority': 'vip'}, timeout=30)
        assert r.status_code == 400 and 'vip' in r.json()['error']
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2], 'max_tokens': 2},
                          headers={'X-Tenant': 'bad tenant!'},
                          timeout=30)
        assert r.status_code == 400
        r = requests.post(base + '/v1/completions',
                          json={'prompt': 'hi', 'max_tokens': 2,
                                'service_tier': 'gold'}, timeout=30)
        assert r.status_code == 400 and 'gold' in r.json()['error']
        # Forced shed via the fault point: batch 429s with
        # Retry-After, interactive unaffected.
        faults.configure('qos.shed=error,where=cls:batch')
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2], 'max_tokens': 2},
                          headers={'X-Priority': 'batch'}, timeout=30)
        assert r.status_code == 429
        assert int(r.headers['Retry-After']) >= 1
        assert r.json()['qos']['action'] == 'shed'
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2], 'max_tokens': 2},
                          headers={'X-Priority': 'interactive'},
                          timeout=60)
        assert r.status_code == 200
        faults.reset()
        # OpenAI route: service_tier=flex maps to batch.
        faults.configure('qos.shed=error,where=cls:batch')
        r = requests.post(base + '/v1/completions',
                          json={'prompt': 'hi', 'max_tokens': 2,
                                'service_tier': 'flex'}, timeout=30)
        assert r.status_code == 429
        faults.reset()
        stats = requests.get(base + '/stats', timeout=10).json()
        assert 'qos' in stats and 'level' in stats['qos']
        assert stats['qos']['classes'] == {
            'interactive': 0, 'standard': 0, 'batch': 0}
        # Shed decisions visible at /metrics by class.
        text = requests.get(base + '/metrics', timeout=10).text
        shed_batch = sum(
            float(line.rsplit(' ', 1)[1]) for line in text.splitlines()
            if line.startswith('skyt_qos_shed_total{class="batch"'))
        assert shed_batch == 2, text
    finally:
        eng.stop()


@pytest.mark.heavy
@pytest.mark.integration
def test_server_qos_off_headers_still_validated(monkeypatch):
    """SKYT_QOS=0: no admission control (no 429 path), but the header
    CONTRACT holds — malformed X-Priority is still a 400 and a valid
    one is accepted."""
    import requests
    from skypilot_tpu.infer import server as server_lib
    monkeypatch.delenv('SKYT_QOS', raising=False)
    reg = metrics_lib.MetricsRegistry()
    eng = _debug_engine(reg)
    eng.start()
    srv = server_lib.InferenceServer(eng)
    assert srv._qos is None   # pylint: disable=protected-access
    port = _free_port()
    _run_app_bg(srv.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    _wait_http(base + '/health', timeout=120)
    try:
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2], 'max_tokens': 2},
                          headers={'X-Priority': 'nope'}, timeout=30)
        assert r.status_code == 400
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2], 'max_tokens': 2},
                          headers={'X-Priority': 'batch',
                                   'X-Tenant': 'team-a'}, timeout=60)
        assert r.status_code == 200
        assert 'qos' not in requests.get(base + '/stats',
                                         timeout=10).json()
    finally:
        eng.stop()


@pytest.mark.heavy
def test_lb_503_carries_retry_after(monkeypatch):
    """Satellite: the LB's no-replica 503 advertises Retry-After
    derived from the sync/backoff state."""
    import requests
    from skypilot_tpu.serve import load_balancer as lb_lib
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')
    monkeypatch.setenv('SKYT_LB_NO_REPLICA_TIMEOUT_S', '0.2')
    reg = metrics_lib.MetricsRegistry()
    port = _free_port()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', port,
                                     metrics_registry=reg)
    _run_app_bg(lb.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            requests.get(base + '/metrics', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.1)
    r = requests.post(base + '/generate', json={'tokens': [1]},
                      timeout=30)
    assert r.status_code == 503
    assert int(r.headers['Retry-After']) >= 1
    del lb


@pytest.mark.heavy
def test_lb_rejects_malformed_priority_and_tracks_demand(monkeypatch):
    """QoS on: the LB 400s malformed X-Priority before proxying and
    records per-class demand for the autoscaler sync."""
    import requests
    from aiohttp import web as aio_web
    from skypilot_tpu.serve import load_balancer as lb_lib
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')
    monkeypatch.setenv('SKYT_QOS', '1')

    async def handler(request):
        del request
        return aio_web.Response(text='ok')

    app = aio_web.Application()
    app.router.add_route('*', '/{p:.*}', handler)
    rport = _free_port()
    _run_app_bg(app, rport)
    replica = f'http://127.0.0.1:{rport}'
    _wait_http(replica + '/x')
    reg = metrics_lib.MetricsRegistry()
    port = _free_port()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', port,
                                     metrics_registry=reg)
    lb.policy.set_ready_replicas([replica])
    _run_app_bg(lb.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            requests.get(base + '/metrics', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.1)
    r = requests.get(base + '/gen',
                     headers={'X-Priority': 'nope'}, timeout=30)
    assert r.status_code == 400
    r = requests.get(base + '/gen',
                     headers={'X-Priority': 'interactive'}, timeout=30)
    assert r.status_code == 200
    assert ('interactive' in
            {cls for _, cls in lb._qos_demand})  # pylint: disable=protected-access


@pytest.mark.heavy
def test_lb_qos_pressure_steers_picks(monkeypatch):
    """A replica advertising level 2 (sheds batch) is avoided for
    batch-class picks while an unpressured replica exists, but still
    used when it is the only one left."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')
    monkeypatch.setenv('SKYT_QOS', '1')
    reg = metrics_lib.MetricsRegistry()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9',
                                     _free_port(),
                                     metrics_registry=reg)
    lb.policy.set_ready_replicas(['http://a', 'http://b'])
    lb._replica_qos = {   # pylint: disable=protected-access
        'http://a': {'level': 2, 'pressure': 0.9}}
    avoid = lb._qos_avoid_for('batch')  # pylint: disable=protected-access
    assert avoid == {'http://a'}
    assert lb._qos_avoid_for('interactive') == set()  # pylint: disable=protected-access
    picks = {lb._pick_replica_once(set(), avoid)  # pylint: disable=protected-access
             for _ in range(4)}
    assert picks == {'http://b'}
    # Only the pressured replica left: pressure avoidance is soft.
    lb.policy.set_ready_replicas(['http://a'])
    assert lb._pick_replica_once(set(), {'http://a'}) == 'http://a'  # pylint: disable=protected-access


def test_controller_sync_payload_roundtrip(monkeypatch):
    """The controller sync handler feeds qos_demand/qos_sheds to the
    autoscaler and returns replica_qos from the prober's scrapes."""
    import asyncio
    from skypilot_tpu.serve import autoscalers
    monkeypatch.setenv('SKYT_QOS', '1')

    class FakeRM:
        def ready_urls(self):
            return ['http://r1']

        def ready_qos(self):
            return {'http://r1': {'level': 2, 'pressure': 0.8}}

        def ready_prefix_cache(self):
            return {'http://r1': {'occupancy': 0.25,
                                  'cached_pages': 4}}

        def ready_weight_versions(self):
            return {'http://r1': 3}

        def ready_adapters(self):
            return {'http://r1': {'summarize': 1}}

    class FakeController:
        def registered_lbs(self):
            return {'lb-a': {'url': 'http://lb-a:8080',
                             'last_sync': time.time()}}

    from skypilot_tpu.serve import controller as controller_lib
    from skypilot_tpu.serve import service_spec as spec_lib
    ctl = FakeController()
    ctl.replica_manager = FakeRM()
    spec = spec_lib.ServiceSpec(readiness_path='/health',
                                min_replicas=1, max_replicas=4,
                                target_qps_per_replica=1.0)
    ctl.autoscaler = autoscalers.QoSAwareAutoscaler(
        spec, metrics_registry=metrics_lib.MetricsRegistry())

    class FakeRequest:
        async def json(self):
            now = time.time()
            return {'request_timestamps': [now],
                    'qos_demand': [[now, 'interactive']],
                    'qos_sheds': [[now, 'batch']]}

    resp = asyncio.new_event_loop().run_until_complete(
        controller_lib.SkyServeController._handle_lb_sync(
            ctl, FakeRequest()))
    import json
    data = json.loads(resp.body)
    assert data['ready_replica_urls'] == ['http://r1']
    assert data['replica_qos']['http://r1']['level'] == 2
    # Prefix-cache occupancy rides the same sync (the LB turns it into
    # skyt_lb_replica_prefix_cache{replica} — ROADMAP item 2 groundwork).
    assert data['replica_prefix_cache']['http://r1']['occupancy'] == \
        0.25
    # Serving weight versions + the registered-LB list (peer
    # discovery) ride the same sync (docs/robustness.md
    # "Zero-downtime rollouts").
    assert data['replica_weight_versions'] == {'http://r1': 3}
    assert data['lbs'] == {'lb-a': 'http://lb-a:8080'}
    assert len(ctl.autoscaler._shed_ts) == 1  # pylint: disable=protected-access
