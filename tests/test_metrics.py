"""Metrics plane tests (utils/metrics.py + its wiring).

Covers the registry itself (label cardinality, histogram bucket math,
golden exposition output, concurrent increments) and the serving
integration: /metrics scrapes cleanly while a completion streams, the
response carries an X-Request-Id whose phase trace /stats returns.
"""
import math
import threading

import pytest

from skypilot_tpu.utils import metrics as metrics_lib


# ------------------------------------------------------------- registry
def test_counter_gauge_basics():
    reg = metrics_lib.MetricsRegistry()
    c = reg.counter('c_total', 'a counter')
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)          # counters only go up
    g = reg.gauge('g', 'a gauge')
    g.set(5)
    g.inc()
    g.dec(3)
    assert g.value() == 3.0


def test_label_cardinality_and_validation():
    reg = metrics_lib.MetricsRegistry()
    c = reg.counter('req_total', 'requests', ('method', 'code'))
    c.labels('GET', '200').inc()
    c.labels('GET', '200').inc()        # same child
    c.labels('POST', '200').inc()       # new child
    c.labels(method='GET', code='500').inc()
    assert c.value('GET', '200') == 2
    assert c.value(method='POST', code='200') == 1
    assert len(c._children) == 3
    with pytest.raises(ValueError):
        c.labels('GET')                  # wrong arity
    with pytest.raises(ValueError):
        c.labels(method='GET', verb='x')  # wrong label names
    with pytest.raises(ValueError):
        c.inc()                          # labeled metric needs labels()
    with pytest.raises(ValueError):
        reg.counter('bad name', 'x')     # invalid metric name
    with pytest.raises(ValueError):
        reg.counter('ok', 'x', ('0bad',))  # invalid label name
    # Same name, different shape -> loud collision, not silent reuse.
    with pytest.raises(ValueError):
        reg.gauge('req_total', 'oops')
    with pytest.raises(ValueError):
        reg.counter('req_total', 'oops', ('method',))
    # Same name, same shape -> get-or-create returns the same object.
    assert reg.counter('req_total', 'requests',
                       ('method', 'code')) is c
    # value() is read-only: an unseen combination reads 0 WITHOUT
    # creating a phantom zero series in the exposition.
    assert c.value('GET', '418') == 0.0
    assert 'code="418"' not in reg.expose()
    with pytest.raises(ValueError):
        c.value('GET')                   # wrong arity still raises


def test_label_eviction():
    """remove_labels drops a churned series from the exposition (the
    LB prunes dead-replica children this way); re-use restarts at 0."""
    reg = metrics_lib.MetricsRegistry()
    c = reg.counter('lb_total', 'x', ('replica',))
    c.labels('http://a:1').inc(5)
    c.labels('http://b:2').inc(3)
    assert sorted(c.label_keys()) == [('http://a:1',), ('http://b:2',)]
    c.remove_labels('http://a:1')
    c.remove_labels('http://gone:9')      # absent -> no-op
    assert c.label_keys() == [('http://b:2',)]
    assert 'http://a:1' not in reg.expose()
    c.labels('http://a:1').inc()          # churned back: fresh series
    assert c.value('http://a:1') == 1


def test_lb_prunes_dead_replica_series():
    from skypilot_tpu.serve import load_balancer as lb_lib
    reg = metrics_lib.MetricsRegistry()
    lb = lb_lib.SkyServeLoadBalancer('http://c', 0,
                                     metrics_registry=reg)
    me = lb.lb_id
    lb._m_requests.labels(me, 'http://r1').inc(4)
    lb._m_errors.labels(me, 'none').inc()
    lb._m_inflight.labels(me, 'http://r1').inc()   # still draining
    lb._m_inflight.labels(me, 'http://r2').inc()
    lb._m_inflight.labels(me, 'http://r2').dec()   # idle
    # Another tier member's series in the SAME registry must survive
    # this LB's prune untouched (the N-active `lb` label contract).
    lb._m_requests.labels('lb-other', 'http://r9').inc()
    lb._prune_replica_metrics(['http://r3'])
    assert lb._m_requests.label_keys() == [('lb-other', 'http://r9')]
    assert lb._m_errors.label_keys() == [(me, 'none')]   # kept
    # Nonzero inflight survives (the drain must dec its own child).
    assert lb._m_inflight.label_keys() == [(me, 'http://r1')]


def test_histogram_bucket_collision():
    reg = metrics_lib.MetricsRegistry()
    h = reg.histogram('lat_seconds', 'x', buckets=(0.1, 1.0))
    # Same buckets (+Inf normalization included) -> same object.
    assert reg.histogram('lat_seconds', 'x', buckets=(0.1, 1.0)) is h
    # Different buckets -> loud collision, not silent mis-bucketing.
    with pytest.raises(ValueError):
        reg.histogram('lat_seconds', 'x', buckets=(10.0, 60.0))


def test_histogram_bucket_math():
    reg = metrics_lib.MetricsRegistry()
    h = reg.histogram('lat_seconds', 'latency', buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 2.0, 100.0):
        h.observe(v)
    child = h.labels()
    # +Inf is appended automatically.
    assert h.buckets == (0.1, 1.0, 10.0, math.inf)
    # Cumulative counts: <=0.1 -> 2 (0.05 and the boundary 0.1),
    # <=1.0 -> 3, <=10 -> 4, +Inf -> 5.
    assert child.cumulative() == [2, 3, 4, 5]
    assert child.count == 5
    assert child.sum == pytest.approx(102.65)


def test_exposition_golden():
    """Exact text exposition 0.0.4 output — the format other tooling
    (Prometheus, the TPU validation scrape) parses."""
    reg = metrics_lib.MetricsRegistry()
    c = reg.counter('skyt_req_total', 'Requests served', ('code',))
    c.labels('200').inc(3)
    c.labels('500').inc()
    g = reg.gauge('skyt_util', 'Utilization (0-1)')
    g.set(0.25)
    h = reg.histogram('skyt_lat_seconds', 'Latency', buckets=(0.5, 2.0))
    h.observe(0.1)
    h.observe(1.0)
    assert reg.expose() == (
        '# HELP skyt_req_total Requests served\n'
        '# TYPE skyt_req_total counter\n'
        'skyt_req_total{code="200"} 3\n'
        'skyt_req_total{code="500"} 1\n'
        '# HELP skyt_util Utilization (0-1)\n'
        '# TYPE skyt_util gauge\n'
        'skyt_util 0.25\n'
        '# HELP skyt_lat_seconds Latency\n'
        '# TYPE skyt_lat_seconds histogram\n'
        'skyt_lat_seconds_bucket{le="0.5"} 1\n'
        'skyt_lat_seconds_bucket{le="2"} 2\n'
        'skyt_lat_seconds_bucket{le="+Inf"} 2\n'
        'skyt_lat_seconds_sum 1.1\n'
        'skyt_lat_seconds_count 2\n')


def test_exposition_escaping():
    reg = metrics_lib.MetricsRegistry()
    c = reg.counter('esc_total', 'help with \\ and\nnewline', ('p',))
    c.labels('a"b\\c\nd').inc()
    text = reg.expose()
    assert '# HELP esc_total help with \\\\ and\\nnewline\n' in text
    assert 'esc_total{p="a\\"b\\\\c\\nd"} 1\n' in text


def test_concurrent_increments():
    """No lost updates under thread contention (the engine loop, HTTP
    handlers, and the control loop all write concurrently)."""
    reg = metrics_lib.MetricsRegistry()
    c = reg.counter('conc_total', 'x', ('t',))
    h = reg.histogram('conc_seconds', 'x', buckets=(0.5,))
    n_threads, n_iter = 8, 2000

    def work(i):
        for _ in range(n_iter):
            c.labels(str(i % 2)).inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value('0') + c.value('1') == n_threads * n_iter
    assert h.labels().count == n_threads * n_iter
    assert h.labels().cumulative()[-1] == n_threads * n_iter


def test_snapshot_shape():
    reg = metrics_lib.MetricsRegistry()
    reg.counter('a_total', 'a', ('x',)).labels('1').inc()
    reg.histogram('b_seconds', 'b', buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert [m['name'] for m in snap] == ['a_total', 'b_seconds']
    assert snap[0]['samples'][0] == {'labels': {'x': '1'}, 'value': 1.0}
    assert snap[1]['samples'][0]['count'] == 1
    assert snap[1]['samples'][0]['buckets']['+Inf'] == 1


def test_autoscaler_decision_counter():
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import service_spec as spec_lib
    reg = metrics_lib.MetricsRegistry()
    spec = spec_lib.ServiceSpec(readiness_path='/health',
                                min_replicas=1, max_replicas=2,
                                target_qps_per_replica=1.0,
                                upscale_delay_seconds=0,
                                downscale_delay_seconds=0)
    a = autoscalers.RequestRateAutoscaler(spec, metrics_registry=reg)
    a.evaluate_scaling(1)                       # steady at min
    import time
    a.collect_request_timestamps([time.time()] * 600)  # 10 qps
    a.evaluate_scaling(1)                       # upscale to max
    dec = reg.get('skyt_autoscaler_decisions_total')
    assert dec.value('steady') == 1
    assert dec.value('upscale') == 1
    assert reg.get('skyt_autoscaler_target_replicas').value() == 2


def test_train_metrics_publisher():
    import jax.numpy as jnp
    from skypilot_tpu.train import trainer
    reg = metrics_lib.MetricsRegistry()
    pub = trainer.TrainMetricsPublisher(registry=reg)
    pub.publish({'loss': jnp.float32(2.5), 'grad_norm': jnp.float32(0.5)},
                step_time_s=0.1, tokens_per_sec=1000.0, steps=10)
    assert reg.get('skyt_train_loss').value() == 2.5
    assert reg.get('skyt_train_grad_norm').value() == 0.5
    assert reg.get('skyt_train_step_seconds').value() == 0.1
    assert reg.get('skyt_train_tokens_per_sec').value() == 1000.0
    assert reg.get('skyt_train_steps_total').value() == 10
