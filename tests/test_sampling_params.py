"""SamplingParams.validate: the engine's honest sampling bounds.

The device sampling path computes top-k and the top-p nucleus from one
shared top-64 sort (engine._TOPK_BUCKET); values it cannot honor
exactly must be rejected at submit time, never silently clamped
(silent clamping gives an OpenAI client asking top_k=200 a different
distribution with no signal). Fast tier: pure parameter logic, no
model.
"""
import pytest

from skypilot_tpu.infer import engine as engine_lib


def test_defaults_valid():
    engine_lib.SamplingParams().validate()


def test_top_k_at_bucket_accepted():
    engine_lib.SamplingParams(top_k=engine_lib._TOPK_BUCKET,
                              temperature=1.0).validate()


@pytest.mark.parametrize('kw,match', [
    (dict(top_k=engine_lib._TOPK_BUCKET + 1), '64'),
    (dict(top_k=-1), 'top_k'),
    (dict(top_k=2.5), 'int'),
    (dict(top_k=True), 'int'),
    (dict(top_p=1.5), 'top_p'),
    (dict(top_p=-0.1), 'top_p'),
    (dict(temperature=-1.0), 'temperature'),
    (dict(max_new_tokens=0), 'max_new_tokens'),
])
def test_invalid_params_rejected(kw, match):
    with pytest.raises(ValueError, match=match):
        engine_lib.SamplingParams(**kw).validate()


def test_submit_rejects_before_enqueue():
    """Engine.submit is the library-level backstop: a bad request must
    raise, not enter the waiting queue."""
    eng = engine_lib.InferenceEngine.__new__(engine_lib.InferenceEngine)
    eng.max_seq_len = 64  # submit() checks params before anything else
    with pytest.raises(ValueError, match='64'):
        eng.submit([1, 2, 3], engine_lib.SamplingParams(top_k=200))
