"""Spec-core tests (mirrors reference tests/unit_tests/test_resources.py)."""
import pytest

from skypilot_tpu import Resources, exceptions
from skypilot_tpu.accelerators import parse_tpu


class TestTpuTopology:
    def test_v5e_16(self):
        t = parse_tpu('tpu-v5e-16')
        assert t.chips == 16
        assert t.num_hosts == 4
        assert t.chips_per_host == 4
        assert t.is_pod
        assert t.gcp_accelerator_type == 'v5litepod-16'

    def test_v5e_single_host(self):
        for size, hosts in ((4, 1), (8, 1)):
            t = parse_tpu(f'tpu-v5e-{size}')
            assert t.num_hosts == hosts
            assert t.chips == size

    def test_core_counted_generations(self):
        # v2/v3/v4/v5p slice names count TensorCores: chips = size/2.
        t = parse_tpu('tpu-v3-32')
        assert t.chips == 16 and t.num_hosts == 4
        t = parse_tpu('tpu-v2-8')
        assert t.chips == 4 and t.num_hosts == 1
        t = parse_tpu('tpu-v4-16')
        assert t.chips == 8 and t.num_hosts == 2
        t = parse_tpu('tpu-v5p-8')
        assert t.chips == 4 and t.num_hosts == 1

    def test_aliases(self):
        assert parse_tpu('tpu-v5litepod-16').name == 'tpu-v5e-16'
        assert parse_tpu('tpu-trillium-8').name == 'tpu-v6e-8'

    def test_non_tpu(self):
        assert parse_tpu('A100') is None
        assert parse_tpu('V100-SXM') is None

    def test_malformed(self):
        with pytest.raises(exceptions.InvalidAcceleratorError):
            parse_tpu('tpu-v99-8')
        with pytest.raises(exceptions.InvalidAcceleratorError):
            parse_tpu('tpu-v5e')

    def test_flops_accounting(self):
        t = parse_tpu('tpu-v5e-16')
        assert t.total_peak_bf16_tflops == pytest.approx(16 * 197.0)


class TestResources:
    def test_tpu_infers_gcp(self):
        r = Resources(accelerators='tpu-v5e-16')
        assert r.cloud == 'gcp'
        assert r.is_tpu
        assert r.num_hosts == 4
        assert r.accelerator_count == 16

    def test_tpu_count_must_be_one(self):
        with pytest.raises(exceptions.InvalidResourcesError):
            Resources(accelerators='tpu-v5e-4:4')

    def test_gpu_accelerator_string(self):
        r = Resources(accelerators='A100:8')
        assert r.accelerators == {'A100': 8}
        assert not r.is_tpu
        assert r.num_hosts == 1

    def test_zone_infers_region(self):
        r = Resources(zone='us-central2-b')
        assert r.region == 'us-central2'

    def test_spot_reserved_exclusive(self):
        with pytest.raises(exceptions.InvalidResourcesError):
            Resources(use_spot=True, reserved=True)

    def test_yaml_round_trip(self):
        r = Resources(accelerators='tpu-v5e-16', use_spot=True,
                      zone='us-west4-a', disk_size=200)
        r2 = Resources.from_yaml_config(r.to_yaml_config())
        assert r2.accelerators == {'tpu-v5e-16': 1}
        assert r2.use_spot and r2.zone == 'us-west4-a'
        assert r2.disk_size == 200

    def test_unknown_field_rejected(self):
        with pytest.raises(exceptions.InvalidResourcesError):
            Resources.from_yaml_config({'acelerators': 'A100'})

    def test_less_demanding_than(self):
        want = Resources(accelerators='tpu-v5e-8')
        have = Resources(accelerators='tpu-v5e-8', zone='us-west4-a')
        assert want.less_demanding_than(have)
        assert not Resources(accelerators='tpu-v5e-16').less_demanding_than(
            have)
        assert not Resources(use_spot=True).less_demanding_than(
            Resources())

    def test_copy_override(self):
        r = Resources(accelerators='tpu-v5e-16')
        r2 = r.copy(zone='us-west4-a', use_spot=True)
        assert r2.zone == 'us-west4-a' and r2.use_spot
        assert r2.tpu_topology.chips == 16
        assert not r.use_spot
