"""Inference-server metrics surface (the serving half of
tests/test_metrics.py, split out beside the other HTTP-surface
integration tests): /metrics scrapes cleanly while a completion
streams, the X-Request-Id header resolves to a phase trace via
/stats?request_id=, and one trace id spans the LB -> replica hop
(utils/tracing.py).
"""
import pytest

from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import tracing as tracing_lib

# ---------------------------------------------------- serving integration
_EXPO_LINE = (r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
              r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
              r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? '
              r'(\+Inf|-Inf|NaN|-?[0-9.e+-]+)$')


def _assert_valid_exposition(text: str) -> None:
    import re
    assert text.endswith('\n')
    for line in text.splitlines():
        if line.startswith('# HELP ') or line.startswith('# TYPE '):
            continue
        assert re.match(_EXPO_LINE, line), f'bad exposition line: {line!r}'


@pytest.mark.integration
def test_metrics_endpoint_while_streaming():
    """GET /metrics returns valid exposition text (TTFT histogram,
    KV-cache utilization gauge included) while a completion streams;
    the stream's X-Request-Id resolves to a full phase trace via
    /stats?request_id=."""
    import dataclasses
    import json
    import socket
    import threading as th
    import time

    import jax
    import jax.numpy as jnp
    import requests
    from aiohttp import web

    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import llama

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    reg = metrics_lib.MetricsRegistry()
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     metrics_registry=reg)
    eng.start()
    srv = server_lib.InferenceServer(eng)
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    th.Thread(target=lambda: web.run_app(
        srv.make_app(), port=port, print=None, handle_signals=False),
        daemon=True).start()
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if requests.get(base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        time.sleep(0.2)

    try:
        resp = requests.post(
            base + '/generate',
            json={'tokens': [9, 9, 9], 'max_tokens': 8, 'stream': True},
            stream=True, timeout=120)
        rid = resp.headers['X-Request-Id']
        tokens = []
        scraped_mid_stream = None
        for line in resp.iter_lines():
            if not line:
                continue
            tokens.append(json.loads(line)['token'])
            if scraped_mid_stream is None:
                # Scrape WHILE the completion is still streaming.
                m = requests.get(base + '/metrics', timeout=5)
                assert m.status_code == 200
                scraped_mid_stream = m.text
        assert len(tokens) == 8
        _assert_valid_exposition(scraped_mid_stream)

        final = requests.get(base + '/metrics', timeout=5)
        assert final.headers['Content-Type'] == metrics_lib.CONTENT_TYPE
        text = final.text
        _assert_valid_exposition(text)
        assert 'skyt_infer_ttft_seconds_bucket{le="+Inf"} 1' in text
        assert '# TYPE skyt_infer_ttft_seconds histogram' in text
        assert '# TYPE skyt_infer_kv_cache_utilization gauge' in text
        assert 'skyt_infer_prefill_tokens_total 3' in text
        # 8 generated = 1 from prefill + 7 from decode chunks.
        assert 'skyt_infer_decode_tokens_total 7' in text

        # Phase trace via /stats?request_id= — the acceptance path.
        tr = requests.get(base + f'/stats?request_id={rid}',
                          timeout=5).json()
        assert tr['queued'] <= tr['prefill_start'] \
            <= tr['first_token'] <= tr['done']
        assert tr['prompt_tokens'] == 3
        assert tr['generated'] == 8
        assert tr['status'] == 'done'
        # Unknown / malformed ids answer 404 / 400, not 500.
        assert requests.get(base + '/stats?request_id=424242',
                            timeout=5).status_code == 404
        assert requests.get(base + '/stats?request_id=nope',
                            timeout=5).status_code == 400
        # Plain /stats still serves the engine summary.
        assert requests.get(base + '/stats',
                            timeout=5).json()['num_slots'] == 2
    finally:
        eng.stop()


@pytest.mark.integration
def test_lb_to_server_trace_propagation(monkeypatch):
    """One request through the serve LB yields ONE trace id visible at
    /debug/traces on BOTH hops: the LB's root span (pick-replica +
    proxy children) and the replica's server + engine phase spans,
    with the server span parented under the LB's proxy span via the
    injected traceparent. With SKYT_TRACE_SLOW_MS=0 the flight
    recorder retains the trace and snapshots engine state onto it."""
    import dataclasses
    import socket
    import threading as th
    import time

    import jax
    import jax.numpy as jnp
    import requests
    from aiohttp import web

    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import llama
    from skypilot_tpu.serve import load_balancer as lb_lib

    monkeypatch.setenv('SKYT_TRACE', '1')
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '1')
    # Everything is 'slow': every trace exercises the flight recorder.
    monkeypatch.setenv('SKYT_TRACE_SLOW_MS', '0')
    # Keep the LB's controller-sync loop from spamming reconnects to
    # the (intentionally absent) controller during the test.
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    reg = metrics_lib.MetricsRegistry()
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     metrics_registry=reg)
    eng.start()
    srv_tracer = tracing_lib.Tracer(service='infer', registry=reg)
    lb_tracer = tracing_lib.Tracer(service='lb', registry=reg)
    srv = server_lib.InferenceServer(eng, tracer=srv_tracer)

    def free_port():
        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            return s.getsockname()[1]

    srv_port, lb_port = free_port(), free_port()
    replica_url = f'http://127.0.0.1:{srv_port}'
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', lb_port, metrics_registry=reg,
        tracer=lb_tracer)
    lb.policy.set_ready_replicas([replica_url])
    for app, port in ((srv.make_app(), srv_port),
                      (lb.make_app(), lb_port)):
        th.Thread(target=lambda a=app, p=port: web.run_app(
            a, port=p, print=None, handle_signals=False),
            daemon=True).start()
    lb_base = f'http://127.0.0.1:{lb_port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            # Health THROUGH the proxy: proves the whole chain is up.
            if requests.get(lb_base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        time.sleep(0.2)

    try:
        resp = requests.post(
            lb_base + '/generate',
            json={'tokens': [5, 6, 7], 'max_tokens': 4}, timeout=120)
        assert resp.status_code == 200
        # Satellite: client-side correlation headers from the LB.
        assert resp.headers['X-Replica-Id'] == replica_url
        # The replica's engine request id wins (it keys /stats).
        assert resp.headers['X-Request-Id'] == \
            str(resp.json()['request_id'])

        # ONE trace id across both hops, found via each hop's own
        # /debug/traces surface.
        lb_summ = requests.get(lb_base + '/debug/traces',
                               timeout=5).json()
        gen = [r for r in lb_summ['recent']
               if r['attributes'].get('http.path') == '/generate']
        assert gen, lb_summ
        tid = gen[0]['trace_id']
        assert gen[0]['slow']                  # flight-recorded at 0ms

        lb_rec = requests.get(
            lb_base + f'/debug/traces?trace_id={tid}', timeout=5).json()
        lb_spans = {s['name']: s for s in lb_rec['spans']}
        assert {'lb.request', 'lb.pick_replica',
                'lb.proxy'} <= set(lb_spans)
        assert lb_spans['lb.request']['parent_id'] is None  # the root
        assert lb_spans['lb.proxy']['parent_id'] == \
            lb_spans['lb.request']['span_id']

        srv_rec = requests.get(
            replica_url + f'/debug/traces?trace_id={tid}',
            timeout=5).json()
        srv_spans = {s['name']: s for s in srv_rec['spans']}
        assert {'server /generate', 'engine.queue_wait',
                'engine.prefill', 'engine.decode'} <= set(srv_spans)
        # The cross-hop parent link: traceparent injected by the LB's
        # proxy span, extracted by the replica's middleware.
        assert srv_spans['server /generate']['parent_id'] == \
            lb_spans['lb.proxy']['span_id']
        for name in ('engine.queue_wait', 'engine.prefill',
                     'engine.decode'):
            assert srv_spans[name]['parent_id'] == \
                srv_spans['server /generate']['span_id']
        # Flight recorder attached an engine-state snapshot.
        snap = srv_rec['state_snapshot']
        assert snap['num_slots'] == 2
        assert 'queue_depth' in snap and 'running_slots' in snap
        # Engine span events (overlap machinery) rode along.
        names = [e['name'] for s in srv_rec['spans']
                 for e in s.get('events', [])]
        assert any(n in ('admission', 'batch_admission',
                         'ragged_admission')
                   for n in names)
        assert 'decode_chunk' in names

        # Chrome dump is Perfetto-loadable trace-event JSON.
        chrome = requests.get(
            replica_url + f'/debug/traces?trace_id={tid}&format=chrome',
            timeout=5).json()
        assert any(e['ph'] == 'X' and e['name'] == 'engine.decode'
                   for e in chrome['traceEvents'])

        # The LB serves its own /metrics (robustness satellite): the
        # retry/breaker families are registered and the per-replica
        # traffic series carries this request.
        lb_text = requests.get(lb_base + '/metrics', timeout=5).text
        assert '# TYPE skyt_lb_retries_total counter' in lb_text
        assert '# TYPE skyt_lb_breaker_state gauge' in lb_text
        assert '# TYPE skyt_lb_breaker_opens_total counter' in lb_text
        assert ('# TYPE skyt_lb_sync_dropped_timestamps_total counter'
                in lb_text)
        assert (f'skyt_lb_requests_total{{lb="{lb.lb_id}",'
                f'replica="{replica_url}"}}' in lb_text)

        # /stats satellite: unknown ids point at the trace surface,
        # malformed ids name the offending value.
        r404 = requests.get(replica_url + '/stats?request_id=424242',
                            timeout=5)
        assert r404.status_code == 404
        assert '/debug/traces?trace_id=' in r404.json()['hint']
        r400 = requests.get(replica_url + '/stats?request_id=nope',
                            timeout=5)
        assert r400.status_code == 400
        assert "'nope'" in r400.json()['error']
    finally:
        eng.stop()
