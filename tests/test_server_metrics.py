"""Inference-server metrics surface (the serving half of
tests/test_metrics.py, split out beside the other HTTP-surface
integration tests): /metrics scrapes cleanly while a completion
streams, and the X-Request-Id header resolves to a phase trace via
/stats?request_id=.
"""
import pytest

from skypilot_tpu.utils import metrics as metrics_lib

# ---------------------------------------------------- serving integration
_EXPO_LINE = (r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
              r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
              r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? '
              r'(\+Inf|-Inf|NaN|-?[0-9.e+-]+)$')


def _assert_valid_exposition(text: str) -> None:
    import re
    assert text.endswith('\n')
    for line in text.splitlines():
        if line.startswith('# HELP ') or line.startswith('# TYPE '):
            continue
        assert re.match(_EXPO_LINE, line), f'bad exposition line: {line!r}'


@pytest.mark.integration
def test_metrics_endpoint_while_streaming():
    """GET /metrics returns valid exposition text (TTFT histogram,
    KV-cache utilization gauge included) while a completion streams;
    the stream's X-Request-Id resolves to a full phase trace via
    /stats?request_id=."""
    import dataclasses
    import json
    import socket
    import threading as th
    import time

    import jax
    import jax.numpy as jnp
    import requests
    from aiohttp import web

    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import llama

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    reg = metrics_lib.MetricsRegistry()
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     metrics_registry=reg)
    eng.start()
    srv = server_lib.InferenceServer(eng)
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    th.Thread(target=lambda: web.run_app(
        srv.make_app(), port=port, print=None, handle_signals=False),
        daemon=True).start()
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if requests.get(base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        time.sleep(0.2)

    try:
        resp = requests.post(
            base + '/generate',
            json={'tokens': [9, 9, 9], 'max_tokens': 8, 'stream': True},
            stream=True, timeout=120)
        rid = resp.headers['X-Request-Id']
        tokens = []
        scraped_mid_stream = None
        for line in resp.iter_lines():
            if not line:
                continue
            tokens.append(json.loads(line)['token'])
            if scraped_mid_stream is None:
                # Scrape WHILE the completion is still streaming.
                m = requests.get(base + '/metrics', timeout=5)
                assert m.status_code == 200
                scraped_mid_stream = m.text
        assert len(tokens) == 8
        _assert_valid_exposition(scraped_mid_stream)

        final = requests.get(base + '/metrics', timeout=5)
        assert final.headers['Content-Type'] == metrics_lib.CONTENT_TYPE
        text = final.text
        _assert_valid_exposition(text)
        assert 'skyt_infer_ttft_seconds_bucket{le="+Inf"} 1' in text
        assert '# TYPE skyt_infer_ttft_seconds histogram' in text
        assert '# TYPE skyt_infer_kv_cache_utilization gauge' in text
        assert 'skyt_infer_prefill_tokens_total 3' in text
        # 8 generated = 1 from prefill + 7 from decode chunks.
        assert 'skyt_infer_decode_tokens_total 7' in text

        # Phase trace via /stats?request_id= — the acceptance path.
        tr = requests.get(base + f'/stats?request_id={rid}',
                          timeout=5).json()
        assert tr['queued'] <= tr['prefill_start'] \
            <= tr['first_token'] <= tr['done']
        assert tr['prompt_tokens'] == 3
        assert tr['generated'] == 8
        assert tr['status'] == 'done'
        # Unknown / malformed ids answer 404 / 400, not 500.
        assert requests.get(base + '/stats?request_id=424242',
                            timeout=5).status_code == 404
        assert requests.get(base + '/stats?request_id=nope',
                            timeout=5).status_code == 400
        # Plain /stats still serves the engine summary.
        assert requests.get(base + '/stats',
                            timeout=5).json()['num_slots'] == 2
    finally:
        eng.stop()
