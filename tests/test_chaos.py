"""Chaos suite: deterministic fault injection (utils/faults.py) and the
fault-tolerant serving/training behaviors it exercises — LB retries on
another replica, per-replica circuit breaker, request deadlines,
client-disconnect cancellation, replica drain/backoff, and
preemption-safe training exits (docs/robustness.md).

The integration tests drive the REAL LB -> server -> engine HTTP stack
on CPU; replica death is a SIGKILL'd subprocess, not a mock.
"""
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest
import requests

from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib

pytestmark = pytest.mark.heavy


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _run_app_bg(app, port) -> None:
    from aiohttp import web
    threading.Thread(target=lambda: web.run_app(
        app, port=port, print=None, handle_signals=False),
        daemon=True).start()


def _wait_http(url: str, timeout: float = 60, proc=None) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc is not None and proc.poll() is not None:
            raise AssertionError(
                f'server died rc={proc.returncode} before {url} was up')
        try:
            if requests.get(url, timeout=2).status_code == 200:
                return
        except requests.RequestException:
            pass
        time.sleep(0.2)
    raise AssertionError(f'{url} never became healthy')


# ================================================== fault spec / triggers
def test_fault_spec_grammar():
    rules = faults.parse_spec(
        'lb.proxy=error,count=2;'
        'engine.loop=latency,arg=0.5,p=0.25,after=10;'
        'server.request=preempt,where=path:/generate')
    assert [r.point for r in rules] == ['lb.proxy', 'engine.loop',
                                       'server.request']
    assert rules[0].kind == 'error' and rules[0].count == 2
    assert rules[1].arg == 0.5 and rules[1].p == 0.25 \
        and rules[1].after == 10
    assert rules[2].where == ('path', '/generate')


@pytest.mark.parametrize('bad', [
    'nokind', 'a.b=doesnotexist', 'a.b=error,p=nope',
    'a.b=error,bogus=1', 'a.b=error,where=novalue', 'a.b=error,p=7',
])
def test_fault_spec_errors(bad):
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_fault_count_and_after_triggers():
    faults.configure('x.y=error,count=2,after=1')
    faults.inject('x.y')                      # after=1: first hit skips
    for _ in range(2):
        with pytest.raises(faults.FaultError):
            faults.inject('x.y')
    faults.inject('x.y')                      # count exhausted
    assert faults.fired_counts() == {('x.y', 'error'): 2}


def test_fault_probability_is_seed_deterministic():
    def pattern():
        faults.configure('x.y=error,p=0.5', seed=7)
        fired = []
        for _ in range(32):
            try:
                faults.inject('x.y')
                fired.append(False)
            except faults.FaultError:
                fired.append(True)
        return fired
    a, b = pattern(), pattern()
    assert a == b            # same seed => identical chaos run
    assert any(a) and not all(a)


def test_fault_where_filter_and_disconnect():
    faults.configure('p.q=disconnect,where=replica:r1')
    faults.inject('p.q', replica='r2')        # filtered out
    faults.inject('p.q')                      # attr absent: filtered
    with pytest.raises(ConnectionResetError):
        faults.inject('p.q', replica='r1')


def test_fault_env_arming_and_malformed_env(monkeypatch):
    monkeypatch.setenv('SKYT_FAULTS', 'e.f=error')
    with pytest.raises(faults.FaultError):
        faults.inject('e.f')
    # Programmatic reset() re-reads the env; clearing it disarms.
    monkeypatch.delenv('SKYT_FAULTS')
    faults.inject('e.f')
    assert not faults.enabled()
    # A malformed env spec is ignored (logged), never raises at the
    # injection site.
    monkeypatch.setenv('SKYT_FAULTS', 'this is not a spec')
    faults.inject('e.f')


def test_fault_fires_are_counted_in_metrics():
    before = metrics_lib.REGISTRY.counter(
        'skyt_faults_fired_total', 'Injected faults fired',
        ('point', 'kind')).value('m.n', 'error')
    faults.configure('m.n=error,count=1')
    with pytest.raises(faults.FaultError):
        faults.inject('m.n')
    after = metrics_lib.REGISTRY.counter(
        'skyt_faults_fired_total', 'Injected faults fired',
        ('point', 'kind')).value('m.n', 'error')
    assert after == before + 1


# ======================================================= circuit breaker
def _breaker(threshold=3, cooldown=0.2):
    from skypilot_tpu.serve import load_balancer as lb_lib
    return lb_lib.CircuitBreaker(threshold=threshold,
                                 cooldown_s=cooldown,
                                 registry=metrics_lib.MetricsRegistry())


def test_breaker_closed_open_halfopen_closed():
    br = _breaker(threshold=3, cooldown=0.15)
    r = 'http://r1'
    for _ in range(2):
        br.record_failure(r)
    assert br.state(r) == br.CLOSED and br.allow(r)
    br.record_failure(r)                       # 3rd consecutive: open
    assert br.state(r) == br.OPEN
    assert not br.allow(r)                     # cooldown not elapsed
    time.sleep(0.2)
    assert br.allow(r)                         # half-open trial granted
    assert br.state(r) == br.HALF_OPEN
    assert not br.allow(r)                     # one trial per window
    br.record_success(r)                       # trial succeeded
    assert br.state(r) == br.CLOSED and br.allow(r)


def test_breaker_blocked_is_read_only():
    """blocked() must never consume the half-open trial: candidate
    filtering checks every ready replica on every pick, and burning
    the trial on replicas the policy then doesn't select would keep a
    recovered replica ejected indefinitely."""
    br = _breaker(threshold=1, cooldown=0.15)
    r = 'http://r1'
    br.record_failure(r)
    time.sleep(0.2)
    for _ in range(10):
        assert not br.blocked(r)       # trial available, not claimed
    assert br.state(r) == br.OPEN      # still no trial in flight
    assert br.allow(r)                 # the actual pick claims it
    assert br.blocked(r)               # now others are filtered out
    br.record_success(r)
    assert not br.blocked(r)


def test_breaker_halfopen_failure_reopens():
    br = _breaker(threshold=1, cooldown=0.15)
    r = 'http://r1'
    br.record_failure(r)
    assert br.state(r) == br.OPEN
    time.sleep(0.2)
    assert br.allow(r)
    br.record_failure(r)                       # trial failed
    assert br.state(r) == br.OPEN
    assert not br.allow(r)                     # window restarted
    # success after a later trial fully resets the failure count
    time.sleep(0.2)
    assert br.allow(r)
    br.record_success(r)
    assert br.state(r) == br.CLOSED


def test_policy_exclude():
    from skypilot_tpu.serve import load_balancing_policies as lbp
    rr = lbp.RoundRobinPolicy()
    rr.set_ready_replicas(['a', 'b', 'c'])
    picks = {rr.select_replica(exclude={'b'}) for _ in range(6)}
    assert picks == {'a', 'c'}
    assert rr.select_replica(exclude={'a', 'b', 'c'}) is None
    lc = lbp.LeastConnectionsPolicy()
    lc.set_ready_replicas(['a', 'b'])
    assert lc.select_replica(exclude={'a'}) == 'b'
    assert lc.select_replica(exclude={'a', 'b'}) is None


# ============================================================ LB behavior
def _make_lb(replicas, monkeypatch=None, **env):
    """In-process LB with a private registry, controller sync parked."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    os.environ.setdefault('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')
    if monkeypatch is not None:
        monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')
        for k, v in env.items():
            monkeypatch.setenv(k, str(v))
    reg = metrics_lib.MetricsRegistry()
    port = _free_port()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', port,
                                     metrics_registry=reg)
    lb.policy.set_ready_replicas(list(replicas))
    _run_app_bg(lb.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            requests.get(base + '/metrics', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.1)
    return lb, base, reg


def _ok_replica(name='ok'):
    """Tiny healthy replica app (no engine: LB behavior under test)."""
    from aiohttp import web

    async def handler(request):
        del request
        return web.Response(text=f'hello-{name}')

    app = web.Application()
    app.router.add_route('*', '/{p:.*}', handler)
    port = _free_port()
    _run_app_bg(app, port)
    url = f'http://127.0.0.1:{port}'
    _wait_http(url + '/x')
    return url


def test_lb_retries_on_another_replica(monkeypatch):
    """A dead replica (connection refused) must be retried on the live
    one with NOTHING visible to the client but the X-Replica-Id of the
    survivor — zero 5xx (tentpole acceptance for pre-header failures).
    """
    dead = f'http://127.0.0.1:{_free_port()}'    # nothing listens
    live = _ok_replica('live')
    lb, base, reg = _make_lb([dead, live], monkeypatch,
                             SKYT_LB_RETRY_BACKOFF_S='0.01')
    for _ in range(6):   # round robin: half land on the dead one first
        r = requests.get(base + '/gen', timeout=10)
        assert r.status_code == 200
        assert r.text == 'hello-live'
        assert r.headers['X-Replica-Id'] == live
    retries = reg.counter('skyt_lb_retries_total', '',
                          ('lb', 'replica'))
    assert retries.value(lb.lb_id, dead) >= 1
    errors = reg.counter('skyt_lb_errors_total', '', ('lb', 'replica'))
    assert errors.value(lb.lb_id, dead) >= 1
    del lb


def test_lb_breaker_opens_and_is_visible_in_metrics(monkeypatch):
    """Consecutive transport failures open the breaker (ejecting the
    replica ahead of the controller sync); state and transition
    counters are scrapeable at the LB's own /metrics."""
    dead = f'http://127.0.0.1:{_free_port()}'
    live = _ok_replica('ok2')
    lb, base, reg = _make_lb([dead, live], monkeypatch,
                             SKYT_LB_RETRY_BACKOFF_S='0.01',
                             SKYT_LB_BREAKER_THRESHOLD='2',
                             SKYT_LB_BREAKER_COOLDOWN_S='30')
    for _ in range(8):
        assert requests.get(base + '/g', timeout=10).status_code == 200
    assert lb.breaker.state(dead) == lb.breaker.OPEN
    requests_m = reg.counter('skyt_lb_requests_total', '',
                             ('lb', 'replica'))
    sent_to_dead = requests_m.value(lb.lb_id, dead)
    # Breaker open: further traffic skips the dead replica entirely.
    for _ in range(4):
        assert requests.get(base + '/g', timeout=10).status_code == 200
    assert requests_m.value(lb.lb_id, dead) == sent_to_dead
    text = requests.get(base + '/metrics', timeout=5).text
    assert (f'skyt_lb_breaker_state{{lb="{lb.lb_id}",'
            f'replica="{dead}"}} 2') in text
    assert (f'skyt_lb_breaker_opens_total{{lb="{lb.lb_id}",'
            f'replica="{dead}"}} 1') in text
    assert 'skyt_lb_retries_total' in text


def test_lb_breaker_halfopen_recovers(monkeypatch):
    """open -> half-open probe -> closed, end to end through the proxy:
    a replica that comes back is restored to rotation after one
    successful half-open trial."""
    from aiohttp import web
    port = _free_port()
    url = f'http://127.0.0.1:{port}'
    lb, base, _reg = _make_lb([url], monkeypatch,
                              SKYT_LB_RETRY_BACKOFF_S='0.01',
                              SKYT_LB_RETRY_BUDGET_S='1',
                              SKYT_LB_BREAKER_THRESHOLD='2',
                              SKYT_LB_BREAKER_COOLDOWN_S='0.3')
    # Nothing listening yet: requests 502 after the budget, breaker
    # opens after 2 transport failures.
    assert requests.get(base + '/g', timeout=10).status_code == 502
    assert lb.breaker.state(url) == lb.breaker.OPEN
    # Replica comes back up ON THE SAME PORT.
    async def handler(request):
        del request
        return web.Response(text='back')
    app = web.Application()
    app.router.add_route('*', '/{p:.*}', handler)
    _run_app_bg(app, port)
    _wait_http(url + '/x')
    time.sleep(0.35)     # past the breaker cooldown
    deadline = time.time() + 10
    while time.time() < deadline:
        r = requests.get(base + '/g', timeout=10)
        if r.status_code == 200:
            break
        time.sleep(0.2)
    assert r.status_code == 200 and r.text == 'back'
    assert lb.breaker.state(url) == lb.breaker.CLOSED


def test_lb_client_disconnect_is_not_a_replica_failure(monkeypatch):
    """A client hanging up mid-proxy must not poison the breaker or
    count as a replica error — with threshold 1, a single
    misclassified disconnect would eject the (healthy) replica."""
    from aiohttp import web

    async def handler(request):
        del request
        import asyncio as aio
        await aio.sleep(0.8)        # slower than the client's patience
        return web.Response(text='slow-ok')

    app = web.Application()
    app.router.add_route('*', '/{p:.*}', handler)
    port = _free_port()
    _run_app_bg(app, port)
    url = f'http://127.0.0.1:{port}'
    time.sleep(0.5)                  # app thread up (handler is slow)
    lb, base, reg = _make_lb([url], monkeypatch,
                             SKYT_LB_BREAKER_THRESHOLD='1')
    for _ in range(3):
        try:
            requests.get(base + '/g', timeout=0.3)   # client gives up
        except requests.RequestException:
            pass
    time.sleep(1.5)   # LB finishes handling the aborted exchanges
    assert lb.breaker.state(url) == lb.breaker.CLOSED
    errors = reg.counter('skyt_lb_errors_total', '', ('lb', 'replica'))
    assert errors.value(lb.lb_id, url) == 0
    disc = reg.counter('skyt_lb_client_disconnects_total', '', ('lb',))
    assert disc.value(lb.lb_id) >= 1
    # A patient client still gets proxied fine.
    r = requests.get(base + '/g', timeout=10)
    assert r.status_code == 200 and r.text == 'slow-ok'


def test_lb_retry_budget_exhaustion(monkeypatch):
    """With every replica down, the client's X-Request-Deadline bounds
    the retry storm: a 502 lands within the budget, not after the
    default 60s."""
    dead1 = f'http://127.0.0.1:{_free_port()}'
    dead2 = f'http://127.0.0.1:{_free_port()}'
    _lb, base, reg = _make_lb([dead1, dead2], monkeypatch,
                              SKYT_LB_RETRY_BACKOFF_S='0.02')
    t0 = time.time()
    r = requests.get(base + '/g', timeout=10,
                     headers={'X-Request-Deadline': '0.6'})
    elapsed = time.time() - t0
    assert r.status_code == 502
    assert 'failed after' in r.text
    assert elapsed < 5, elapsed
    retries = reg.counter('skyt_lb_retries_total', '',
                          ('lb', 'replica'))
    assert retries.value(_lb.lb_id, dead1) + \
        retries.value(_lb.lb_id, dead2) >= 1


def test_lb_no_replica_timeout_env(monkeypatch):
    """Satellite: the no-replica 503 deadline/poll are env knobs, not
    the hardcoded 30s/1s."""
    _lb, base, _reg = _make_lb([], monkeypatch,
                               SKYT_LB_NO_REPLICA_TIMEOUT_S='0.3',
                               SKYT_LB_NO_REPLICA_POLL_S='0.05')
    t0 = time.time()
    r = requests.get(base + '/g', timeout=10)
    assert r.status_code == 503
    assert 'No available replicas' in r.text
    assert time.time() - t0 < 3


def test_lb_timestamp_buffer_cap(monkeypatch):
    """Satellite: the unsent-timestamp buffer is bounded; overflow
    drops oldest and counts skyt_lb_sync_dropped_timestamps_total."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    monkeypatch.setenv('SKYT_LB_MAX_PENDING_TIMESTAMPS', '10')
    reg = metrics_lib.MetricsRegistry()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', 1,
                                     metrics_registry=reg)
    lb.request_timestamps = list(range(25))
    lb._cap_timestamps()  # pylint: disable=protected-access
    assert lb.request_timestamps == list(range(15, 25))
    dropped = reg.counter('skyt_lb_sync_dropped_timestamps_total', '',
                          ('lb',))
    assert dropped.value(lb.lb_id) == 15


# ===================================================== replica lifecycle
def test_drain_grace_semantics(tmp_state_dir, monkeypatch):
    """A deliberately retired READY replica leaves the ready set
    immediately but its teardown waits the drain grace; failed
    replicas are torn down without grace."""
    del tmp_state_dir
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib
    serve_state.reset_db_for_testing()
    monkeypatch.setenv('SKYT_SERVE_DRAIN_GRACE_S', '0.5')
    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)
    serve_state.add_service('dsvc', spec, '/tmp/none.yaml', 1, 2)
    downed = []
    from skypilot_tpu import core as core_lib
    monkeypatch.setattr(
        core_lib, 'down',
        lambda name, purge=False: downed.append((name, time.time())))
    mgr = replica_managers.ReplicaManager('dsvc', spec, '/tmp/none.yaml')
    info = replica_managers.ReplicaInfo(
        replica_id=1, cluster_name='dsvc-1', version=1,
        status=serve_state.ReplicaStatus.READY,
        endpoint='http://127.0.0.1:1')
    mgr.replicas[1] = info
    t0 = time.time()
    mgr.terminate_replica(1, drain=True)
    # Ready set empties NOW (LB stops routing at its next sync) ...
    assert mgr.ready_urls() == []
    assert info.status is serve_state.ReplicaStatus.SHUTTING_DOWN
    deadline = time.time() + 10
    while not downed and time.time() < deadline:
        time.sleep(0.05)
    # ... but the actual teardown waited the grace period.
    assert downed and downed[0][1] - t0 >= 0.45
    reg = mgr._m_drains  # pylint: disable=protected-access
    assert reg.value('dsvc') == 1
    # Non-drain teardown (failure path) skips the grace.
    info2 = replica_managers.ReplicaInfo(
        replica_id=2, cluster_name='dsvc-2', version=1,
        status=serve_state.ReplicaStatus.NOT_READY,
        endpoint='http://127.0.0.1:2')
    mgr.replicas[2] = info2
    t1 = time.time()
    mgr.terminate_replica(2, sync=True, drain=True)  # not READY: no grace
    assert len(downed) == 2 and downed[1][1] - t1 < 0.4
    assert reg.value('dsvc') == 1


def test_relaunch_backoff_gates_reconcile(tmp_state_dir, monkeypatch):
    """Probe-failure -> FAILED relaunches go through exponential
    backoff instead of a tight launch loop; a READY replica resets it.
    """
    del tmp_state_dir
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib
    serve_state.reset_db_for_testing()
    monkeypatch.setenv('SKYT_SERVE_RELAUNCH_BACKOFF_S', '30')
    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)
    serve_state.add_service('bsvc', spec, '/tmp/none.yaml', 1, 2)
    mgr = replica_managers.ReplicaManager('bsvc', spec, '/tmp/none.yaml')
    launches = []
    monkeypatch.setattr(mgr, 'launch_replica',
                        lambda use_spot=None: launches.append(1))
    mgr.reconcile(target=1)
    assert len(launches) == 1            # no failures yet: launches
    mgr._note_replica_failed()           # pylint: disable=protected-access
    mgr.reconcile(target=1)
    assert len(launches) == 1            # gated by the backoff
    mgr._next_launch_ok = 0.0            # pylint: disable=protected-access
    mgr.reconcile(target=1)
    assert len(launches) == 2            # gate expired: launches again


# ============================================= real stack: engine deadline
def _debug_engine(reg, decode_chunk=2):
    import dataclasses
    import jax
    import jax.numpy as jnp
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.models import llama
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    return engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=64,
                                      decode_chunk=decode_chunk,
                                      prefill_buckets=[16],
                                      metrics_registry=reg)


@pytest.mark.integration
def test_request_deadline_frees_slot():
    """A request past its deadline is cancelled by the decode loop: the
    slot frees, the trace records status='deadline', and the deadline
    counter ticks. A slow engine is simulated with an injected
    per-tick latency fault (dogfooding the subsystem under test)."""
    from skypilot_tpu.infer import engine as engine_lib
    faults.configure('engine.loop=latency,arg=0.05')
    reg = metrics_lib.MetricsRegistry()
    eng = _debug_engine(reg)
    eng.start()
    try:
        rid, q = eng.submit([3, 4, 5], engine_lib.SamplingParams(
            max_new_tokens=1000,
            deadline=time.time() + 0.4))
        toks = []
        deadline = time.time() + 30
        while time.time() < deadline:
            item = q.get(timeout=30)
            if item is None:
                break
            toks.append(item)
        assert len(toks) < 60          # expired before the length cap
        tr = eng.request_trace(rid)
        assert tr['status'] == 'deadline'
        assert eng.stats()['active_slots'] == 0
        expired = reg.counter('skyt_infer_deadline_expired_total', '')
        assert expired.value() == 1
    finally:
        eng.stop()


@pytest.mark.integration
def test_server_deadline_header_and_disconnect():
    """HTTP layer: malformed X-Request-Deadline 400s before submit; a
    tiny deadline yields a 200 with PARTIAL tokens (the engine freed
    the slot); a client disconnect mid-stream cancels the engine
    request and frees the slot instead of generating into a dead
    socket."""
    from skypilot_tpu.infer import server as server_lib

    faults.configure('engine.loop=latency,arg=0.05')
    reg = metrics_lib.MetricsRegistry()
    eng = _debug_engine(reg)
    eng.start()
    srv = server_lib.InferenceServer(eng)
    port = _free_port()
    _run_app_bg(srv.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    _wait_http(base + '/health', timeout=60)
    try:
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2, 3], 'max_tokens': 4},
                          headers={'X-Request-Deadline': 'soon'},
                          timeout=10)
        assert r.status_code == 400
        assert "'soon'" in r.json()['error']

        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2, 3],
                                'max_tokens': 1000},
                          headers={'X-Request-Deadline': '0.4'},
                          timeout=60)
        assert r.status_code == 200
        assert 0 < len(r.json()['tokens']) < 60

        # Mid-stream disconnect: read a couple of chunks, then drop
        # the connection; the engine request must cancel (slot frees).
        resp = requests.post(
            base + '/generate',
            json={'tokens': [5, 6, 7], 'max_tokens': 1000,
                  'stream': True},
            stream=True, timeout=60)
        it = resp.iter_lines()
        next(it)
        next(it)
        resp.close()
        deadline = time.time() + 20
        while time.time() < deadline:
            if eng.stats()['active_slots'] == 0:
                break
            time.sleep(0.1)
        assert eng.stats()['active_slots'] == 0
        disconnects = reg.counter(
            'skyt_server_client_disconnects_total', '')
        assert disconnects.value() >= 1
    finally:
        eng.stop()


def test_fault_event_lands_on_server_span(monkeypatch):
    """A server.request fault fired with tracing on must leave its
    `fault.<kind>` event on THAT request's server span (the injection
    runs inside the tracing middleware's span, not in the outermost
    metrics middleware where no span exists yet) — otherwise a chaos
    run's slowdowns are unexplainable at /debug/traces."""
    from skypilot_tpu.infer import server as server_lib

    monkeypatch.setenv('SKYT_TRACE', '1')
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '1')
    monkeypatch.setenv('SKYT_TRACE_SLOW_MS', '0')
    faults.configure(
        'server.request=latency,arg=0.01,where=path:/generate')
    reg = metrics_lib.MetricsRegistry()
    eng = _debug_engine(reg)
    eng.start()
    srv = server_lib.InferenceServer(eng)
    port = _free_port()
    _run_app_bg(srv.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    try:
        _wait_http(base + '/health')
        r = requests.post(base + '/generate',
                          json={'tokens': [1, 2, 3], 'max_tokens': 4},
                          timeout=60)
        assert r.status_code == 200
        summaries = requests.get(base + '/debug/traces',
                                 timeout=5).json()['recent']
        gen = [t for t in summaries
               if t['attributes'].get('http.path') == '/generate']
        assert gen, summaries
        detail = requests.get(
            base + f"/debug/traces?trace_id={gen[0]['trace_id']}",
            timeout=5).json()
        events = [(s['name'], e['name']) for s in detail['spans']
                  for e in s.get('events', [])]
        assert ('server /generate', 'fault.latency') in events, events
    finally:
        eng.stop()


# ======================================== control plane: crash recovery
def test_fault_crash_kind_sigkills_process():
    """The new 'crash' kind is a true SIGKILL — no handlers, no
    cleanup — distinct from 'preempt' (SIGTERM, catchable)."""
    proc = subprocess.run(
        [sys.executable, '-c',
         'from skypilot_tpu.utils import faults\n'
         "faults.configure('x.y=crash')\n"
         "faults.inject('x.y')\n"
         "print('survived')"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == -signal.SIGKILL, proc
    assert 'survived' not in proc.stdout


def test_lbstate_snapshot_roundtrip():
    """LBState is the serializable controller-synced view a standby
    mirrors; age survives the JSON round trip (monotonic stamps don't
    transfer between processes — age does)."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    state = lb_lib.LBState(
        ready_replicas=['http://r1', 'http://r2'],
        replica_qos={'http://r1': {'level': 2}},
        replica_weight_version={'http://r1': 2, 'http://r2': 1},
        synced_at=time.monotonic() - 5.0, version=7)
    restored = lb_lib.LBState.from_json(state.to_json())
    assert restored.ready_replicas == state.ready_replicas
    assert restored.replica_qos == state.replica_qos
    assert restored.replica_weight_version == \
        state.replica_weight_version
    assert restored.version == 7
    assert 4.0 < restored.age_s() < 7.0
    # Fresh state: nothing to be stale about.
    assert lb_lib.LBState().age_s() == 0.0
    # Garbage weight versions are dropped, not crashed on.
    mangled = lb_lib.LBState.from_json(
        '{"ready_replicas": ["http://r1"], '
        '"replica_weight_version": {"http://r1": "bogus", '
        '"http://r2": 4}}')
    assert mangled.replica_weight_version == {'http://r2': 4}


def test_lb_peer_discovery_from_sync(monkeypatch):
    """`--lb-peers auto`: the tier's advertise URLs come from the
    controller's registered-LB list on each sync; a manual list keeps
    discovery off; own URL and own lb_id are filtered out."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    reg = metrics_lib.MetricsRegistry()
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1', 18080, metrics_registry=reg,
        lb_id='lb-me', peers=['auto'])
    assert lb.peer_discovery and lb.peers == []
    lb._discover_peers({  # pylint: disable=protected-access
        'lb-me': 'http://127.0.0.1:18080',        # own id: dropped
        'lb-b': 'http://h2:18081/',
        'lb-c': 'http://h3:18082'})
    assert lb.peers == ['http://h2:18081', 'http://h3:18082']
    # Membership churn propagates on the next sync.
    lb._discover_peers({'lb-b': 'http://h2:18081'})  # pylint: disable=protected-access
    assert lb.peers == ['http://h2:18081']
    # Garbage payloads are ignored.
    lb._discover_peers(['not', 'a', 'dict'])  # pylint: disable=protected-access
    assert lb.peers == ['http://h2:18081']
    # Manual list: discovery off, sync lists ignored.
    lb2 = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1', 18090, metrics_registry=reg,
        lb_id='lb-2', peers=['http://manual:1'])
    assert not lb2.peer_discovery
    lb2._discover_peers({'lb-x': 'http://h9:1'})  # pylint: disable=protected-access
    assert lb2.peers == ['http://manual:1']
    # And weight versions land on the per-replica gauge via
    # apply_state, pruned with the snapshot.
    lb.apply_state(lb_lib.LBState(
        ready_replicas=['http://r1'],
        replica_weight_version={'http://r1': 5},
        synced_at=time.monotonic()))
    gauge = reg.gauge('skyt_lb_replica_weight_version', '',
                      ('lb', 'replica'))
    assert gauge.value('lb-me', 'http://r1') == 5
    lb.apply_state(lb_lib.LBState(
        ready_replicas=['http://r2'],
        replica_weight_version={'http://r2': 6},
        synced_at=time.monotonic()))
    assert ('lb-me', 'http://r1') not in gauge.label_keys()
    assert gauge.value('lb-me', 'http://r2') == 6


def test_lb_stale_mode_serves_and_recovers(monkeypatch):
    """Controller partition (the `lb.sync` fault point): the LB must
    keep serving the last-known ready set instead of draining to 503s,
    surface the mode in /metrics + /debug/lb_state, and leave it the
    moment the sync heals."""
    from aiohttp import web

    from skypilot_tpu.serve import load_balancer as lb_lib

    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '0.2')
    monkeypatch.setenv('SKYT_LB_STALE_PROBE_TIMEOUT_S', '1')
    live = _ok_replica('stale-live')

    # Fake controller the LB really syncs from.
    ctrl_port = _free_port()

    async def sync_handler(request):
        del request
        return web.json_response({'ready_replica_urls': [live]})

    ctrl_app = web.Application()
    ctrl_app.router.add_post('/controller/load_balancer_sync',
                             sync_handler)
    _run_app_bg(ctrl_app, ctrl_port)

    reg = metrics_lib.MetricsRegistry()
    lb_port = _free_port()
    lb = lb_lib.SkyServeLoadBalancer(
        f'http://127.0.0.1:{ctrl_port}', lb_port, metrics_registry=reg)
    _run_app_bg(lb.make_app(), lb_port)
    base = f'http://127.0.0.1:{lb_port}'
    deadline = time.time() + 30
    while time.time() < deadline and \
            lb.policy.ready_replicas != [live]:
        time.sleep(0.1)
    assert lb.policy.ready_replicas == [live]

    # Partition: every further sync fails at the fault point.
    faults.configure('lb.sync=error')
    deadline = time.time() + 30
    while time.time() < deadline and not lb._stale:  # pylint: disable=protected-access
        time.sleep(0.1)
    assert lb._stale  # pylint: disable=protected-access

    # Degraded, not down: the stale replica set still serves, and the
    # mode is visible to operators and traces.
    for _ in range(4):
        r = requests.get(base + '/g', timeout=10)
        assert r.status_code == 200 and r.text == 'hello-stale-live'
    state = requests.get(base + '/debug/lb_state', timeout=5).json()
    assert state['stale'] is True
    assert state['ready_replicas'] == [live]
    assert f'skyt_lb_stale{{lb="{lb.lb_id}"}} 1' in requests.get(
        base + '/metrics', timeout=5).text

    # Sync heals: stale mode exits, fresh state applies.
    faults.reset()
    deadline = time.time() + 30
    while time.time() < deadline and lb._stale:  # pylint: disable=protected-access
        time.sleep(0.1)
    assert not lb._stale  # pylint: disable=protected-access
    assert f'skyt_lb_stale{{lb="{lb.lb_id}"}} 0' in requests.get(
        base + '/metrics', timeout=5).text


def test_lb_stale_probe_prunes_dead_replica(monkeypatch):
    """Stale-mode health probes: a replica that dies while the
    controller is partitioned away is pruned from the stale ready set
    (no traffic pinned on a corpse for the whole partition)."""
    from skypilot_tpu.serve import load_balancer as lb_lib

    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '0.2')
    monkeypatch.setenv('SKYT_LB_STALE_PROBE_TIMEOUT_S', '1')
    monkeypatch.setenv('SKYT_LB_RETRY_BACKOFF_S', '0.01')
    live = _ok_replica('sp-live')
    # A REAL subprocess replica we can kill mid-partition.
    dead_port = _free_port()
    dead_proc = subprocess.Popen(
        [sys.executable, '-c',
         'import http.server, sys\n'
         'class H(http.server.BaseHTTPRequestHandler):\n'
         '    def do_GET(self):\n'
         '        self.send_response(200); self.end_headers()\n'
         '    def log_message(self, *a): pass\n'
         f'http.server.HTTPServer(("127.0.0.1", {dead_port}), '
         'H).serve_forever()'])
    dead = f'http://127.0.0.1:{dead_port}'
    ctrl_port = _free_port()

    from aiohttp import web

    async def sync_handler(request):
        del request
        return web.json_response({'ready_replica_urls': [live, dead]})

    ctrl_app = web.Application()
    ctrl_app.router.add_post('/controller/load_balancer_sync',
                             sync_handler)
    _run_app_bg(ctrl_app, ctrl_port)

    reg = metrics_lib.MetricsRegistry()
    lb_port = _free_port()
    lb = lb_lib.SkyServeLoadBalancer(
        f'http://127.0.0.1:{ctrl_port}', lb_port, metrics_registry=reg,
        stale_probe_path='/')     # the service's readiness contract
    _run_app_bg(lb.make_app(), lb_port)
    try:
        _wait_http(dead + '/x')
        deadline = time.time() + 30
        while time.time() < deadline and \
                sorted(lb.policy.ready_replicas) != sorted([live, dead]):
            time.sleep(0.1)
        assert sorted(lb.policy.ready_replicas) == sorted([live, dead])
        # Partition, then kill the replica DURING it.
        faults.configure('lb.sync=error')
        deadline = time.time() + 30
        while time.time() < deadline and not lb._stale:  # pylint: disable=protected-access
            time.sleep(0.1)
        dead_proc.kill()
        dead_proc.wait(timeout=30)
        deadline = time.time() + 30
        while time.time() < deadline and \
                dead in lb.policy.ready_replicas:
            time.sleep(0.1)
        assert lb.policy.ready_replicas == [live]
        pruned = reg.counter('skyt_lb_stale_pruned_total', '', ('lb',))
        assert pruned.value(lb.lb_id) >= 1
        # And traffic still flows on the survivor.
        r = requests.get(f'http://127.0.0.1:{lb_port}/g', timeout=10)
        assert r.status_code == 200 and r.text == 'hello-sp-live'
    finally:
        faults.reset()
        if dead_proc.poll() is None:
            dead_proc.kill()


def test_lb_stale_probe_threshold_recovery_and_no_contract(monkeypatch):
    """Stale-mode pruning discipline: (a) a replica is pruned only
    after SKYT_LB_STALE_PROBE_THRESHOLD CONSECUTIVE failures (one slow
    probe under partition load must not drop a loaded replica), (b) a
    pruned replica that recovers is RE-ADDED (probe rounds cover the
    full snapshot, not just survivors), (c) with no readiness contract
    configured the snapshot is served untouched — probing a path the
    replicas never promised would prune healthy ones."""
    import asyncio as aio

    import aiohttp
    from aiohttp import web

    from skypilot_tpu.serve import load_balancer as lb_lib

    monkeypatch.setenv('SKYT_LB_STALE_PROBE_THRESHOLD', '3')
    monkeypatch.setenv('SKYT_LB_STALE_PROBE_TIMEOUT_S', '1')
    health = {'ok': True}

    async def hc(request):
        del request
        return web.Response(status=200 if health['ok'] else 500)

    app = web.Application()
    app.router.add_get('/hc', hc)
    port = _free_port()
    _run_app_bg(app, port)
    url = f'http://127.0.0.1:{port}'
    _wait_http(url + '/hc')

    async def run():
        reg = metrics_lib.MetricsRegistry()
        lb = lb_lib.SkyServeLoadBalancer(
            'http://127.0.0.1:9', 1, metrics_registry=reg,
            stale_probe_path='/hc')
        lb._session = aiohttp.ClientSession()  # pylint: disable=protected-access
        try:
            lb.apply_state(lb_lib.LBState(
                ready_replicas=[url], synced_at=time.monotonic()))
            health['ok'] = False
            for i in range(2):
                await lb._prune_stale_replicas()  # pylint: disable=protected-access
                assert lb.policy.ready_replicas == [url], \
                    f'pruned after only {i + 1} failure(s)'
            await lb._prune_stale_replicas()  # pylint: disable=protected-access
            assert lb.policy.ready_replicas == []     # 3rd: pruned
            pruned = reg.counter('skyt_lb_stale_pruned_total', '',
                                 ('lb',))
            assert pruned.value(lb.lb_id) == 1
            # Recovery: the next round re-probes the full snapshot and
            # re-admits the healed replica.
            health['ok'] = True
            await lb._prune_stale_replicas()  # pylint: disable=protected-access
            assert lb.policy.ready_replicas == [url]
            assert pruned.value(lb.lb_id) == 1        # no double count

            # No contract, no env override: pruning is a no-op even
            # with a stone-dead replica in the snapshot.
            lb2 = lb_lib.SkyServeLoadBalancer(
                'http://127.0.0.1:9', 1,
                metrics_registry=metrics_lib.MetricsRegistry())
            lb2._session = lb._session  # pylint: disable=protected-access
            dead = f'http://127.0.0.1:{_free_port()}'
            lb2.apply_state(lb_lib.LBState(
                ready_replicas=[dead], synced_at=time.monotonic()))
            await lb2._prune_stale_replicas()  # pylint: disable=protected-access
            assert lb2.policy.ready_replicas == [dead]
        finally:
            await lb._session.close()  # pylint: disable=protected-access

    aio.run(run())


def test_lb_stale_ttl_drains(monkeypatch):
    """A stale snapshot older than SKYT_LB_STALE_TTL_S stops being
    served: a too-old world view is worse than an honest 503."""
    import asyncio as aio

    from skypilot_tpu.serve import load_balancer as lb_lib

    monkeypatch.setenv('SKYT_LB_STALE_TTL_S', '0.2')
    reg = metrics_lib.MetricsRegistry()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', 1,
                                     metrics_registry=reg)
    lb.apply_state(lb_lib.LBState(
        ready_replicas=['http://r1'], synced_at=time.monotonic() - 10))
    assert lb.policy.ready_replicas == ['http://r1']
    aio.run(lb._enter_or_hold_stale())  # pylint: disable=protected-access
    assert lb.policy.ready_replicas == []
    assert reg.gauge('skyt_lb_stale', '',
                     ('lb',)).value(lb.lb_id) == 1


def test_leader_lease_survives_nothing_flock_released_on_kill(tmp_path):
    """LeaderLease is kernel-backed: SIGKILLing the holder releases the
    flock instantly, and a waiting standby acquires on its next poll —
    no heartbeat-expiry guessing."""
    from skypilot_tpu.serve import load_balancer as lb_lib

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lease_path = str(tmp_path / 'x.lease')
    holder = subprocess.Popen(
        [sys.executable, '-c',
         'import sys, time\n'
         f'sys.path.insert(0, {repo!r})\n'
         'from skypilot_tpu.serve import load_balancer as lb_lib\n'
         f'lease = lb_lib.LeaderLease({lease_path!r})\n'
         'assert lease.try_acquire()\n'
         "print('HELD', flush=True)\n"
         'time.sleep(3600)'],
        stdout=subprocess.PIPE, text=True)
    try:
        assert holder.stdout.readline().strip() == 'HELD'
        ours = lb_lib.LeaderLease(lease_path, interval_s=0.1)
        assert not ours.try_acquire()          # leader alive: denied
        info = ours.holder()
        assert info and info['pid'] == holder.pid
        holder.kill()
        holder.wait(timeout=30)
        deadline = time.time() + 5
        while time.time() < deadline and not ours.try_acquire():
            time.sleep(0.05)
        assert ours.held                       # takeover ≤ one interval
        ours.heartbeat()
        assert ours.holder()['pid'] == os.getpid()
        ours.release()
    finally:
        if holder.poll() is None:
            holder.kill()


def test_restart_adopts_live_and_reaps_orphans(tmp_state_dir,
                                               monkeypatch):
    """Restart adoption truth table, in-process: a live probed replica
    with a matching pid identity is ADOPTED (no relaunch); a dead-pid
    row is reaped even though its endpoint still answers (pid identity
    wins over a lucky probe); a stale-spec-version row is reaped; the
    `replica.orphan` fault point forces the reap path on demand."""
    del tmp_state_dir
    from skypilot_tpu import core as core_lib
    from skypilot_tpu import state as cluster_state
    from skypilot_tpu.runtime import reaper
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    serve_state.reset_db_for_testing()
    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=4,
                                probe_timeout_seconds=2)
    serve_state.add_service('rsvc', spec, '/t.yaml', 1, 2)
    live_url = _ok_replica('adopt')
    me = os.getpid()
    token = reaper.pid_start_token(me)

    def row(rid, **kw):
        info = replica_managers.ReplicaInfo(
            replica_id=rid, cluster_name=f'rsvc-{rid}', version=1,
            status=serve_state.ReplicaStatus.READY,
            endpoint=live_url, pid=me, pid_start=token)
        for k, v in kw.items():
            setattr(info, k, v)
        serve_state.upsert_replica('rsvc', rid, info)

    row(1)                                     # adoptable
    row(2, pid=999999)                         # dead pid, live endpoint
    row(3)                                     # fault-forced orphan
    row(4, version=2)                          # stale spec version
    # FAILED row whose teardown the old controller never finished:
    # must be reaped (cluster torn down), not leaked until the prune
    # sweep erases the only record of it.
    row(5, status=serve_state.ReplicaStatus.FAILED)
    faults.configure('replica.orphan=error,where=replica:3')
    monkeypatch.setattr(cluster_state, 'get_cluster',
                        lambda name: {'handle': None})
    downed = []
    monkeypatch.setattr(core_lib, 'down',
                        lambda name, purge=False: downed.append(name))
    reg = metrics_lib.MetricsRegistry()
    mgr = replica_managers.ReplicaManager(
        'rsvc', spec, '/t.yaml', metrics_registry=reg)
    assert mgr.replicas[1].status is serve_state.ReplicaStatus.READY
    assert mgr.replicas[1].adopted_at is not None
    adoptions = reg.counter('skyt_serve_replica_adoptions_total', '',
                            ('service',))
    reaps = reg.counter('skyt_serve_replica_reaps_total', '',
                        ('service', 'reason'))
    assert adoptions.value('rsvc') == 1
    assert reaps.value('rsvc', 'dead_pid') == 1
    assert reaps.value('rsvc', 'fault_injected') == 1
    assert reaps.value('rsvc', 'stale_spec_version') == 1
    assert reaps.value('rsvc', 'failed_pre_restart') == 1
    # Reaped rows head to teardown, not the ready set.
    assert mgr.ready_urls() == [live_url]
    deadline = time.time() + 10
    while time.time() < deadline and len(downed) < 4:
        time.sleep(0.05)
    assert sorted(downed) == ['rsvc-2', 'rsvc-3', 'rsvc-4', 'rsvc-5']


# The replica task for control-plane drills: a dumb 200-everything
# HTTP server (same shape as tests/test_serve.py REPLICA_SERVER).
_REPLICA_SERVER = (
    "python -c \""
    "import http.server, os;\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        self.send_response(200); self.end_headers();\n"
    "        self.wfile.write(('hello-from-' + "
    "os.environ['SKYT_REPLICA_PORT']).encode())\n"
    "    def do_POST(self):\n"
    "        self.do_GET()\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYT_REPLICA_PORT'])), H).serve_forever()\"")


@pytest.fixture()
def control_plane_env(tmp_path, tmp_state_dir, monkeypatch):
    """Local-provider serve environment with fast control loops, for
    drills that run the real controller as a killable subprocess."""
    del tmp_state_dir
    from skypilot_tpu import state
    from skypilot_tpu.serve import serve_state
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))
    monkeypatch.setenv('SKYT_DEFAULT_STORE', 'local')
    monkeypatch.setenv('SKYT_SERVE_CONTROLLER_INTERVAL', '0.3')
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '0.3')
    state.reset_db_for_testing()
    serve_state.reset_db_for_testing()
    yield tmp_path
    from skypilot_tpu import core as core_lib
    for rec in state.get_clusters():
        try:
            core_lib.down(rec['name'], purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
    state.reset_db_for_testing()
    serve_state.reset_db_for_testing()


def _spawn_service(name, role):
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.serve.service',
         '--service-name', name, '--role', role],
        env=dict(os.environ), stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)


def _wait_replicas_ready(name, want, timeout=120):
    from skypilot_tpu.serve import serve_state
    deadline = time.time() + timeout
    while time.time() < deadline:
        infos = serve_state.get_replicas(name)
        ready = [r for r in infos
                 if r.status is serve_state.ReplicaStatus.READY]
        if len(ready) >= want:
            return ready
        time.sleep(0.5)
    raise AssertionError(
        f'{want} replicas never READY: '
        f'{[(r.replica_id, r.status) for r in serve_state.get_replicas(name)]}')


@pytest.mark.integration
def test_chaos_controller_sigkill_adoption_zero_relaunches(
        control_plane_env):
    """THE control-plane acceptance drill: SIGKILL the controller
    mid-burst. In-flight and subsequent requests keep succeeding
    through the LB's stale-state mode (0 client-visible 5xx, replicas
    were never touched), and a restarted controller ADOPTS every READY
    replica — zero relaunches, asserted via /controller/metrics."""
    import yaml as yaml_lib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    tmp_path = control_plane_env
    task = sky.Task(name='ccp', run=_REPLICA_SERVER)
    task.set_resources(resources_lib.Resources(cloud='local'))
    spec = spec_lib.ServiceSpec(
        readiness_path='/', min_replicas=2,
        initial_delay_seconds=60, probe_timeout_seconds=2)
    task.service = spec
    task_yaml = str(tmp_path / 'ccp.task.yaml')
    with open(task_yaml, 'w', encoding='utf-8') as f:
        yaml_lib.safe_dump(task.to_yaml_config(), f)
    cport, lport = _free_port(), _free_port()
    assert serve_state.add_service('ccp', spec, task_yaml, cport, lport)
    token = serve_state.get_service('ccp')['auth_token']

    ctrl = _spawn_service('ccp', 'controller')
    lb = None
    try:
        _wait_replicas_ready('ccp', 2)
        # The LB runs in OUR process (it must survive the controller
        # kill), syncing from the real controller.
        reg = metrics_lib.MetricsRegistry()
        lb_port = _free_port()
        lb = lb_lib.SkyServeLoadBalancer(
            f'http://127.0.0.1:{cport}', lb_port,
            controller_auth=token, metrics_registry=reg)
        _run_app_bg(lb.make_app(), lb_port)
        base = f'http://127.0.0.1:{lb_port}'
        deadline = time.time() + 60
        while time.time() < deadline and \
                len(lb.policy.ready_replicas) < 2:
            time.sleep(0.2)
        assert len(lb.policy.ready_replicas) == 2

        results = []
        lock = threading.Lock()

        def one(i):
            r = requests.get(base + f'/burst-{i}', timeout=60)
            with lock:
                results.append(r.status_code)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(12)]
        for th in threads[:4]:
            th.start()
        # The chaos event: controller dies mid-burst, no grace.
        ctrl.kill()
        for th in threads[4:]:
            th.start()
        for th in threads:
            th.join(timeout=120)
        ctrl.wait(timeout=30)
        assert results == [200] * 12, results

        # The LB noticed the partition and kept serving stale state.
        deadline = time.time() + 30
        while time.time() < deadline and not lb._stale:  # pylint: disable=protected-access
            time.sleep(0.2)
        assert lb._stale  # pylint: disable=protected-access
        r = requests.get(base + '/after-death', timeout=30)
        assert r.status_code == 200

        # Restart: the new controller must ADOPT, not relaunch.
        ctrl = _spawn_service('ccp', 'controller')
        _wait_replicas_ready('ccp', 2)
        headers = {'Authorization': f'Bearer {token}'}
        deadline = time.time() + 60
        metrics_text = ''
        while time.time() < deadline:
            try:
                metrics_text = requests.get(
                    f'http://127.0.0.1:{cport}/controller/metrics',
                    headers=headers, timeout=5).text
                if ('skyt_serve_replica_adoptions_total'
                        '{service="ccp"} 2') in metrics_text:
                    break
            except requests.RequestException:
                pass
            time.sleep(0.5)
        assert ('skyt_serve_replica_adoptions_total{service="ccp"} 2'
                in metrics_text), metrics_text
        # Zero relaunches: the launch counter never ticked in the
        # restarted process, and no reap happened.
        assert 'skyt_serve_replica_launches_total{service="ccp"}' \
            not in metrics_text, metrics_text
        # (sample lines carry labels — the bare name also appears in
        # HELP/TYPE headers, so match the labeled form)
        assert 'skyt_serve_replica_reaps_total{' not in metrics_text, \
            metrics_text
        # Same replica ids as before the crash — really the same
        # replicas, not lookalikes.
        ready = _wait_replicas_ready('ccp', 2)
        assert {r.replica_id for r in ready} == {1, 2}
        assert all(r.adopted_at is not None for r in ready)
        # And the healed sync pulls the LB out of stale mode.
        deadline = time.time() + 30
        while time.time() < deadline and lb._stale:  # pylint: disable=protected-access
            time.sleep(0.2)
        assert not lb._stale  # pylint: disable=protected-access
        assert requests.get(base + '/after-restart',
                            timeout=30).status_code == 200
    finally:
        if ctrl.poll() is None:
            ctrl.kill()
        del lb


@pytest.mark.integration
def test_controller_crash_fault_point_fires(control_plane_env,
                                            monkeypatch):
    """`SKYT_FAULTS=controller.crash=crash` SIGKILLs the controller
    from inside its own control loop — the arm-it-and-watch way to run
    the restart-adoption drill without test scaffolding kills."""
    import yaml as yaml_lib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    tmp_path = control_plane_env
    task = sky.Task(name='crsvc', run='sleep 3600')
    task.set_resources(resources_lib.Resources(cloud='local'))
    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=0,
                                max_replicas=1,
                                target_qps_per_replica=1.0)
    task.service = spec
    task_yaml = str(tmp_path / 'crsvc.task.yaml')
    with open(task_yaml, 'w', encoding='utf-8') as f:
        yaml_lib.safe_dump(task.to_yaml_config(), f)
    assert serve_state.add_service('crsvc', spec, task_yaml,
                                   _free_port(), _free_port())
    monkeypatch.setenv('SKYT_FAULTS', 'controller.crash=crash,after=2')
    ctrl = _spawn_service('crsvc', 'controller')
    try:
        ctrl.wait(timeout=120)
        assert ctrl.returncode == -signal.SIGKILL, ctrl.returncode
    finally:
        if ctrl.poll() is None:
            ctrl.kill()


@pytest.mark.integration
def test_lb_standby_takes_over_port(tmp_state_dir, monkeypatch):
    """Hot-standby failover: two `--role lb` processes; the leader
    owns the port, the standby mirrors LBState via the same controller
    sync. SIGKILL the leader → the standby takes over the port within
    ~one lease interval and serves the same replica set."""
    from aiohttp import web

    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service as service_lib
    from skypilot_tpu.serve import service_spec as spec_lib

    del tmp_state_dir
    serve_state.reset_db_for_testing()
    monkeypatch.setenv('SKYT_LB_LEASE_INTERVAL_S', '0.2')
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '0.3')
    replica = _ok_replica('standby-drill')
    cport, lport = _free_port(), _free_port()
    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)
    assert serve_state.add_service('sbsvc', spec, '/t.yaml', cport,
                                   lport)

    async def sync_handler(request):
        del request
        return web.json_response({'ready_replica_urls': [replica]})

    ctrl_app = web.Application()
    ctrl_app.router.add_post('/controller/load_balancer_sync',
                             sync_handler)
    _run_app_bg(ctrl_app, cport)

    lbs = [_spawn_service('sbsvc', 'lb') for _ in range(2)]
    base = f'http://127.0.0.1:{lport}'
    lease_path = service_lib.lb_lease_path('sbsvc')
    try:
        _wait_http(base + '/g', timeout=120)
        r = requests.get(base + '/g', timeout=10)
        assert r.status_code == 200 and r.text == 'hello-standby-drill'
        with open(lease_path, 'r', encoding='utf-8') as f:
            leader_pid = __import__('json').loads(f.read())['pid']
        assert leader_pid in [p.pid for p in lbs]
        standby_pid = next(p.pid for p in lbs if p.pid != leader_pid)

        os.kill(leader_pid, signal.SIGKILL)
        t0 = time.time()
        deadline = t0 + 30
        took_over = None
        while time.time() < deadline:
            try:
                r = requests.get(base + '/g', timeout=5)
                if r.status_code == 200:
                    took_over = time.time() - t0
                    break
            except requests.RequestException:
                pass
            time.sleep(0.1)
        assert took_over is not None, 'standby never took the port'
        assert r.text == 'hello-standby-drill'
        with open(lease_path, 'r', encoding='utf-8') as f:
            assert __import__('json').loads(f.read())['pid'] == \
                standby_pid
        # The new leader advertises leadership on its own /metrics.
        assert f'skyt_lb_leader{{lb="lb-{lport}"}} 1' in requests.get(
            base + '/metrics', timeout=5).text
    finally:
        for p in lbs:
            if p.poll() is None:
                p.kill()
        serve_state.remove_service('sbsvc')


# ======================================= N-active LB tier (front door)
def test_lb_gossip_partition_and_reconverge(monkeypatch):
    """Two active LBs exchanging LBState via gossip. Partition BOTH
    planes (`lb.sync=error` + `lb.gossip=error`): each LB keeps
    serving from its own stale view (degraded, never down), the peer
    views age past SKYT_LB_PEER_STALE_S and leave the aggregates.
    Heal: stale mode exits and the peers reconverge to fresh."""
    from aiohttp import web

    from skypilot_tpu.serve import load_balancer as lb_lib

    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '0.2')
    monkeypatch.setenv('SKYT_LB_PEER_SYNC_S', '0.2')
    monkeypatch.setenv('SKYT_LB_PEER_STALE_S', '0.6')
    live = _ok_replica('gsp')
    ctrl_port = _free_port()

    async def sync_handler(request):
        del request
        return web.json_response({'ready_replica_urls': [live]})

    ctrl_app = web.Application()
    ctrl_app.router.add_post('/controller/load_balancer_sync',
                             sync_handler)
    _run_app_bg(ctrl_app, ctrl_port)

    ports = [_free_port(), _free_port()]
    urls = [f'http://127.0.0.1:{p}' for p in ports]
    lbs = []
    for port, peer in zip(ports, reversed(urls)):
        lb = lb_lib.SkyServeLoadBalancer(
            f'http://127.0.0.1:{ctrl_port}', port,
            policy='prefix_affinity',
            metrics_registry=metrics_lib.MetricsRegistry(),
            peers=[peer])
        _run_app_bg(lb.make_app(), port)
        lbs.append(lb)

    def states():
        return [requests.get(u + '/debug/lb_state', timeout=5).json()
                for u in urls]

    def all_fresh(sts):
        return all(s['ready_replicas'] == [live] and s['peers'] and
                   all(p['fresh'] for p in s['peers'].values())
                   for s in sts)

    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if all_fresh(states()):
                break
        except requests.RequestException:
            pass            # LB apps still binding
        time.sleep(0.2)
    assert all_fresh(states()), states()

    # Full partition: controller sync AND gossip fail everywhere.
    faults.configure('lb.sync=error;lb.gossip=error')
    deadline = time.time() + 30
    while time.time() < deadline:
        sts = states()
        if all(s['stale'] for s in sts) and \
                not any(p['fresh'] for s in sts
                        for p in s['peers'].values()):
            break
        time.sleep(0.2)
    sts = states()
    assert all(s['stale'] for s in sts), sts
    assert not any(p['fresh'] for s in sts
                   for p in s['peers'].values()), sts
    # Degraded, not down: BOTH keep serving their stale views.
    for u in urls:
        r = requests.get(u + '/g', timeout=10)
        assert r.status_code == 200 and r.text == 'hello-gsp'

    # Heal: stale mode exits and the tier reconverges.
    faults.reset()
    deadline = time.time() + 30
    while time.time() < deadline:
        sts = states()
        if not any(s['stale'] for s in sts) and all_fresh(sts):
            break
        time.sleep(0.2)
    sts = states()
    assert not any(s['stale'] for s in sts), sts
    assert all_fresh(sts), sts
    del lbs


def test_lb_gossip_rejects_unauthenticated_and_unconfigured(monkeypatch):
    """/lb/gossip lives on the CLIENT-facing port: with the service
    token configured it 401s unauthenticated senders, and payloads
    whose advertised URL is not in the configured peer list never
    become a PeerView — an arbitrary client must not be able to
    poison the routing view or grow the peer table."""
    from skypilot_tpu.serve import load_balancer as lb_lib

    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')
    port = _free_port()
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', port, controller_auth='sekrit',
        metrics_registry=metrics_lib.MetricsRegistry(),
        peers=['http://127.0.0.1:1'])
    _run_app_bg(lb.make_app(), port)
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            requests.get(base + '/metrics', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.1)
    forged = {'lb_id': 'evil', 'url': 'http://attacker:80',
              'state': {'ready_replicas': ['http://attacker:80'],
                        'age_s': 0.0}}
    r = requests.post(base + '/lb/gossip', json=forged, timeout=5)
    assert r.status_code == 401
    assert lb._peer_views == {}  # pylint: disable=protected-access
    # Right token, but the sender's URL is not a configured peer:
    # answered (push-pull still works mid-rolling-update), absorbed
    # NOT — no PeerView, no poisoned avoid set, no adopted state.
    r = requests.post(base + '/lb/gossip', json=forged, timeout=5,
                      headers={'Authorization': 'Bearer sekrit'})
    assert r.status_code == 200
    assert lb._peer_views == {}  # pylint: disable=protected-access
    # A configured peer with the token IS absorbed.
    ok = {'lb_id': 'lb-1', 'url': 'http://127.0.0.1:1',
          'state': {'ready_replicas': ['http://r1'], 'age_s': 0.0}}
    r = requests.post(base + '/lb/gossip', json=ok, timeout=5,
                      headers={'Authorization': 'Bearer sekrit'})
    assert r.status_code == 200
    assert list(lb._peer_views) == ['lb-1']  # pylint: disable=protected-access


def _spawn_lb(name, port, peer_urls, extra_env=None):
    env = dict(os.environ)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.serve.service',
         '--service-name', name, '--role', 'lb',
         '--lb-port', str(port), '--lb-peers', ','.join(peer_urls)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)


@pytest.mark.integration
def test_chaos_n_active_lb_sigkill_mid_burst(tmp_state_dir,
                                             monkeypatch):
    """THE front-door acceptance drill (docs/robustness.md "Front
    door"): 3 ACTIVE LB processes (prefix_affinity ring, peer gossip)
    serving a concurrent burst; one SIGKILLs itself mid-burst via the
    `lb.crash` fault point. Clients that fail over to a surviving LB
    see ZERO 5xx, the same affinity key keeps routing to the same
    replica through every survivor (deterministic ring — the dead
    LB's traffic is absorbed with affinity intact), and the dead peer
    leaves the survivors' fresh-peer sets within one exchange
    interval + staleness bound."""
    from aiohttp import web

    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    del tmp_state_dir
    serve_state.reset_db_for_testing()
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '0.2')
    monkeypatch.setenv('SKYT_LB_PEER_SYNC_S', '0.2')
    monkeypatch.setenv('SKYT_LB_PEER_STALE_S', '1.0')
    r1, r2 = _ok_replica('na-r1'), _ok_replica('na-r2')
    ctrl_port = _free_port()
    spec = spec_lib.ServiceSpec(
        readiness_path='/', min_replicas=2,
        load_balancing_policy='prefix_affinity')
    assert serve_state.add_service('nasvc', spec, '/t.yaml',
                                   ctrl_port, _free_port())

    ctrl_up = {'ok': True}   # flipped to partition the controller

    async def sync_handler(request):
        del request
        if not ctrl_up['ok']:
            return web.json_response({'error': 'partitioned'},
                                     status=503)
        return web.json_response({
            'ready_replica_urls': [r1, r2],
            'replica_prefix_cache': {r1: {'occupancy': 0.4},
                                     r2: {'occupancy': 0.1}}})

    ctrl_app = web.Application()
    ctrl_app.router.add_post('/controller/load_balancer_sync',
                             sync_handler)
    _run_app_bg(ctrl_app, ctrl_port)

    ports = [_free_port() for _ in range(3)]
    urls = [f'http://127.0.0.1:{p}' for p in ports]
    procs = []
    for i, port in enumerate(ports):
        peers = [u for u in urls if u != urls[i]]
        extra = None
        if i == 0:
            # The chaos event comes from INSIDE: the first LB SIGKILLs
            # itself on its 4th proxied request (lb.crash fires in the
            # proxy path only — /debug and /lb/gossip don't count).
            extra = {'SKYT_FAULTS': 'lb.crash=crash,after=3'}
        procs.append(_spawn_lb('nasvc', port, peers, extra_env=extra))

    def lb_state(u, timeout=5):
        return requests.get(u + '/debug/lb_state',
                            timeout=timeout).json()

    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                sts = [lb_state(u) for u in urls]
                if all(sorted(s['ready_replicas']) == sorted([r1, r2])
                       and sum(1 for p in s['peers'].values()
                               if p['fresh']) == 2 for s in sts):
                    break
            except requests.RequestException:
                pass
            time.sleep(0.3)
        else:
            raise AssertionError('N-active tier never converged')

        # Ring consistency across the tier, pre-kill: the same keyed
        # body routes to the SAME replica through the two LBs that
        # will survive (the doomed one must not see proxy traffic
        # before the burst).
        keyed = {'tokens': [7, 8, 9], 'max_tokens': 2}
        homes = {requests.post(u + '/gen', json=keyed,
                               timeout=10).headers['X-Replica-Id']
                 for u in urls[1:]}
        assert len(homes) == 1, homes
        home = homes.pop()

        results = []
        lock = threading.Lock()

        def one(i):
            # A front-door client: try LBs in order until one answers
            # (the VIP/DNS failover a real deployment has). Transport
            # errors against a dead LB are expected; an HTTP 5xx from
            # a SURVIVOR is the failure this drill exists to catch.
            for attempt, u in enumerate(
                    urls[i % 3:] + urls[:i % 3]):
                try:
                    r = requests.post(
                        u + f'/burst-{i}', json=keyed
                        if i % 2 == 0 else {'tokens': [i], 'n': i},
                        headers={'X-Session-Id': f'sess-{i % 4}'},
                        timeout=30)
                    with lock:
                        results.append(
                            (r.status_code,
                             r.headers.get('X-Replica-Id')))
                    return
                except requests.RequestException:
                    continue
            with lock:
                results.append((599, None))   # no LB answered at all

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(24)]
        for th in threads[:8]:
            th.start()
        # lb.crash fires inside procs[0] during this window.
        for th in threads[8:]:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert len(results) == 24
        codes = [c for c, _ in results]
        # Zero client-visible 5xx: every request landed 200 on SOME
        # active LB.
        assert codes == [200] * 24, codes

        # The fault actually fired: LB 0 died by SIGKILL.
        deadline = time.time() + 30
        while time.time() < deadline and procs[0].poll() is None:
            time.sleep(0.2)
        assert procs[0].returncode == -signal.SIGKILL, \
            procs[0].returncode

        # Survivors drop the dead peer from their fresh sets within
        # one exchange interval + the staleness bound.
        dead_id = f'lb-{ports[0]}'
        deadline = time.time() + 30
        while time.time() < deadline:
            sts = [lb_state(u) for u in urls[1:]]
            if all(not s['peers'].get(dead_id, {}).get('fresh', True)
                   for s in sts):
                break
            time.sleep(0.2)
        sts = [lb_state(u) for u in urls[1:]]
        assert all(not s['peers'].get(dead_id, {}).get('fresh', True)
                   for s in sts), sts
        # Ring reconvergence: both survivors still route the key to
        # its pre-kill home (replicas never churned, so no key moved).
        for u in urls[1:]:
            r = requests.post(u + '/gen', json=keyed, timeout=10)
            assert r.status_code == 200
            assert r.headers['X-Replica-Id'] == home
            assert lb_state(u)['ring']['nodes'], 'ring emptied'

        # Same window, second chaos event: the CONTROLLER partitions.
        # Both survivors must degrade to per-LB stale mode — still
        # serving the full healthy replica set, nothing drained.
        ctrl_up['ok'] = False
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(lb_state(u)['stale'] for u in urls[1:]):
                break
            time.sleep(0.2)
        for u in urls[1:]:
            s = lb_state(u)
            assert s['stale'], s
            assert sorted(s['ready_replicas']) == sorted([r1, r2]), \
                'stale mode drained healthy replicas'
            r = requests.post(u + '/gen', json=keyed, timeout=10)
            assert r.status_code == 200
            assert r.headers['X-Replica-Id'] == home
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        serve_state.remove_service('nasvc')


# ================================================ preemption guard modes
def test_preemption_guard_immediate_exit_during_startup():
    """Startup phase (immediate=True): SIGTERM exits with
    EXIT_CODE_PREEMPTED on the spot — no step boundary is coming for
    minutes during weight streaming / first compile, and burning the
    preemption grace window there ends in SIGKILL + FAILED.
    cooperative() then hands the exit back to the step loop."""
    from skypilot_tpu.runtime.job_lib import EXIT_CODE_PREEMPTED
    from skypilot_tpu.train import checkpoint as ckpt_lib

    if threading.current_thread() is not threading.main_thread():
        pytest.skip('signal handlers need the main thread')
    guard = ckpt_lib.PreemptionGuard(immediate=True)
    try:
        with pytest.raises(SystemExit) as exc:
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 10
            while time.time() < deadline:   # handler needs a bytecode
                time.sleep(0.001)           # boundary on this thread
            pytest.fail('immediate guard never fired')
        assert exc.value.code == EXIT_CODE_PREEMPTED
        assert guard.requested and guard.signum == signal.SIGTERM
    finally:
        guard.restore()

    guard = ckpt_lib.PreemptionGuard(immediate=True)
    try:
        guard.cooperative()   # step loop started: flag-only from here
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 10
        while not guard.requested and time.time() < deadline:
            time.sleep(0.001)
        assert guard.requested
    finally:
        guard.restore()


# ==================================== real stack: replica kill mid-burst
def _spawn_replica(port: int, extra_env=None,
                   max_seq_len: int = 64) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.infer.server',
         '--model', 'debug', '--port', str(port),
         '--num-slots', '2', '--max-seq-len', str(max_seq_len)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.mark.integration
def test_chaos_replica_kill_mid_burst(monkeypatch):
    """The acceptance scenario: a burst through the REAL LB -> server
    -> engine stack while one of two replica PROCESSES is SIGKILLed
    mid-burst. Every request whose response headers had not been sent
    completes on the surviving replica — zero client-visible 5xx —
    and the breaker opens on the dead replica."""
    p1, p2 = _free_port(), _free_port()
    procs = [_spawn_replica(p1), _spawn_replica(p2)]
    url1, url2 = (f'http://127.0.0.1:{p1}', f'http://127.0.0.1:{p2}')
    try:
        for proc, url in zip(procs, (url1, url2)):
            _wait_http(url + '/health', timeout=180, proc=proc)
        lb, base, reg = _make_lb([url1, url2], monkeypatch,
                                 SKYT_LB_RETRY_BACKOFF_S='0.02',
                                 SKYT_LB_BREAKER_THRESHOLD='2',
                                 SKYT_LB_BREAKER_COOLDOWN_S='30')
        results = []
        lock = threading.Lock()

        def one(i):
            r = requests.post(
                base + '/generate',
                json={'tokens': [i + 1, i + 2, i + 3],
                      'max_tokens': 8},
                timeout=60)
            with lock:
                results.append((r.status_code,
                                r.headers.get('X-Replica-Id')))

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(12)]
        for i, th in enumerate(threads[:4]):
            th.start()
        # Kill replica 1 mid-burst (SIGKILL: no graceful anything).
        procs[0].kill()
        for th in threads[4:]:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert len(results) == 12
        # Zero client-visible 5xx: every pre-header failure was
        # retried onto the survivor.
        assert all(code == 200 for code, _ in results), results
        survivors = {rep for code, rep in results}
        assert url2 in survivors
        # The breaker opened on the dead replica well before any
        # controller sync could eject it.
        assert lb.breaker.state(url1) == lb.breaker.OPEN
        text = requests.get(base + '/metrics', timeout=5).text
        assert (f'skyt_lb_breaker_state{{lb="{lb.lb_id}",'
                f'replica="{url1}"}} 2') in text
        retries = reg.counter('skyt_lb_retries_total', '',
                              ('lb', 'replica'))
        assert retries.value(lb.lb_id, url1) >= 1
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


@pytest.mark.integration
def test_chaos_interference_survives_replica_kill():
    """Tick-plane drill (docs/observability.md "Tick plane"): a
    mid-burst replica SIGKILL must not poison the survivor's
    interference accounting. The survivor's pure-decode baselines stay
    warm and finite, fresh requests still get a decode-floor/
    interference ITL split, and the fleet rollup ages the dead replica
    out past the stale horizon instead of carrying its frozen series
    into the advisor's inputs forever."""
    from skypilot_tpu.serve import fleet as fleet_lib

    class Clock:
        def __init__(self):
            self.t = time.time()

        def __call__(self):
            return self.t

    p1, p2 = _free_port(), _free_port()
    tick_env = {'SKYT_TICKSTATS': '1',
                'SKYT_INTERFERENCE_MIN_SAMPLES': '2'}
    procs = [_spawn_replica(p1, tick_env), _spawn_replica(p2, tick_env)]
    urls = [f'http://127.0.0.1:{p1}', f'http://127.0.0.1:{p2}']
    try:
        for proc, url in zip(procs, urls):
            _wait_http(url + '/health', timeout=180, proc=proc)
        # Warm both replicas: multi-chunk decodes give every tick/ITL
        # series a first scrape edge and warm the decode baselines.
        for url in urls:
            for _ in range(3):
                requests.post(
                    url + '/generate',
                    json={'tokens': [5, 6, 7], 'max_tokens': 24},
                    timeout=120).raise_for_status()
        clock = Clock()
        fl = fleet_lib.FleetTelemetry(
            'chaos', metrics_registry=metrics_lib.MetricsRegistry(),
            clock=clock)
        assert fl.scrape('0', urls[0])
        assert fl.scrape('1', urls[1])

        def burst(url):
            for i in range(30):
                try:
                    requests.post(
                        url + '/generate',
                        json={'tokens': [i % 13 + 2, 3, 4],
                              'max_tokens': 16},
                        timeout=30)
                except requests.RequestException:
                    pass   # in-flight work on the killed replica

        threads = [threading.Thread(target=burst, args=(u,))
                   for u in urls for _ in range(2)]
        for th in threads:
            th.start()
        time.sleep(1.0)
        procs[0].kill()   # SIGKILL mid-burst: no graceful anything
        for th in threads:
            th.join(timeout=180)

        time.sleep(0.3)
        clock.t += 40
        assert not fl.scrape('0', urls[0])   # dead: scrape fails
        assert fl.scrape('1', urls[1])

        # Survivor's baselines are warm, finite, and un-poisoned.
        summ = requests.get(urls[1] + '/debug/ticks?last=16',
                            timeout=10).json()['summary']
        assert summ['ticks'] > 0
        assert summ['baselines'], summ
        for b in summ['baselines'].values():
            assert 0.0 < b['ewma_s'] < 5.0, summ['baselines']
        # Fresh work after the kill still accrues an ITL split.
        before = summ['classes']['standard']['decode_floor_s']
        requests.post(urls[1] + '/generate',
                      json={'tokens': [9, 9, 9], 'max_tokens': 24},
                      timeout=120).raise_for_status()
        after = requests.get(urls[1] + '/debug/ticks?last=1',
                             timeout=10).json()['summary']
        assert after['classes']['standard']['decode_floor_s'] > before

        # Rollup at the scrape horizon: both targets present, the
        # survivor's families advanced through the burst.
        rep = fl.interference_report(window_s=600, now=clock.t)
        t1 = rep['targets']['1']
        assert sum(t1['ticks'].values()) > 0
        assert t1['itl_split'], t1
        assert t1['advisor']['recommendation'] in (
            'disaggregate', 'keep_colocated', 'insufficient_data')

        # Past the stale horizon the dead replica ages out of the
        # rollup; the recently-scraped survivor stays.
        rep2 = fl.interference_report(window_s=600,
                                      now=clock.t + fl.stale_s - 5)
        assert '0' not in rep2['targets'], sorted(rep2['targets'])
        assert '1' in rep2['targets'], sorted(rep2['targets'])
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


@pytest.mark.integration
def test_chaos_batch_flood_sheds_only_batch(monkeypatch):
    """QoS acceptance scenario (docs/qos.md) through the REAL LB ->
    server -> engine stack: a batch-class flood against one replica
    with SKYT_QOS=1 and aggressive shed thresholds. Every interactive
    request must succeed (zero 429/5xx) while batch sheds are > 0 —
    visible in the replica's /metrics AND in the LB's observed-shed
    counter (the QoS-aware autoscaler's scale-up signal)."""
    port = _free_port()
    proc = _spawn_replica(port, extra_env={
        'SKYT_QOS': '1',
        'SKYT_QOS_QUEUE_DEGRADE': '1',
        'SKYT_QOS_QUEUE_SHED': '2',
        'SKYT_QOS_DEGRADE_MAX_TOKENS': '4',
        'SKYT_QOS_RESERVE_SLOTS': '1',
        'SKYT_QOS_REFRESH_S': '0.05',
        'SKYT_QOS_HOLD_S': '5',
        # Queue depth drives the drill; the debug model's TTFT jitter
        # must not escalate the ladder on its own.
        'SKYT_QOS_TTFT_SLO_MS': '0',
    })
    url = f'http://127.0.0.1:{port}'
    try:
        _wait_http(url + '/health', timeout=180, proc=proc)
        lb, base, reg = _make_lb([url], monkeypatch, SKYT_QOS='1')
        stop = threading.Event()

        def flood():
            s = requests.Session()
            while not stop.is_set():
                try:
                    r = s.post(base + '/generate',
                               json={'tokens': [3, 4, 5],
                                     'max_tokens': 48},
                               headers={'X-Priority': 'batch',
                                        'X-Tenant': 'flooder'},
                               timeout=60)
                    if r.status_code == 429:
                        # Well-behaved batch clients honor Retry-After
                        # (capped so the flood persists through the
                        # interactive probes).
                        time.sleep(min(float(
                            r.headers.get('Retry-After', 1)), 0.25))
                except requests.RequestException:
                    pass

        flooders = [threading.Thread(target=flood, daemon=True)
                    for _ in range(6)]
        for th in flooders:
            th.start()
        time.sleep(2.0)             # let the backlog build + ladder arm
        sess = requests.Session()
        codes = []
        for i in range(10):
            r = sess.post(base + '/generate',
                          json={'tokens': [i + 1, i + 2],
                                'max_tokens': 4},
                          headers={'X-Priority': 'interactive'},
                          timeout=120)
            codes.append(r.status_code)
        stop.set()
        for th in flooders:
            th.join(timeout=60)
        # Zero interactive 429/5xx: the flood only ever sheds batch.
        assert codes == [200] * 10, codes
        text = requests.get(url + '/metrics', timeout=5).text

        def shed(cls):
            total = 0.0
            for line in text.splitlines():
                if line.startswith(
                        f'skyt_qos_shed_total{{class="{cls}"'):
                    total += float(line.rsplit(' ', 1)[1])
            return total

        assert shed('batch') > 0, 'batch flood never shed'
        assert shed('interactive') == 0, 'interactive was shed'
        # The LB saw the upstream 429s and attributed them to the
        # batch class (the autoscaler's shed-rate signal).
        observed = reg.counter('skyt_lb_qos_sheds_observed_total', '',
                               ('lb', 'class'))
        assert observed.value(lb.lb_id, 'batch') > 0
        assert observed.value(lb.lb_id, 'interactive') == 0
        del lb
    finally:
        if proc.poll() is None:
            proc.kill()


@pytest.mark.integration
def test_chaos_flash_crowd_sheds_only_sheddable_class(monkeypatch):
    """Capacity-plane acceptance drill (docs/observability.md
    "Capacity plane"): a deterministic workload-engine schedule with a
    20x flash-crowd step, replayed open-loop through the REAL
    in-process LB -> server -> engine stack with SKYT_QOS=1. The
    protected interactive class rides through the step with zero
    429/5xx, only the sheddable batch class sheds (and the sheds land
    inside the crowd window), and both classes serve again after the
    crowd passes."""
    from skypilot_tpu.benchmark import workload

    port = _free_port()
    proc = _spawn_replica(port, extra_env={
        'SKYT_QOS': '1',
        # Aggressive thresholds sized to the 2-slot debug replica:
        # batch sheds as soon as 2 requests queue (ratio q/slots >= 1).
        'SKYT_QOS_QUEUE_DEGRADE': '0.5',
        'SKYT_QOS_QUEUE_SHED': '1',
        'SKYT_QOS_DEGRADE_MAX_TOKENS': '4',
        'SKYT_QOS_RESERVE_SLOTS': '1',
        'SKYT_QOS_REFRESH_S': '0.05',
        'SKYT_QOS_HOLD_S': '2',
        'SKYT_QOS_TTFT_SLO_MS': '0',
    })
    url = f'http://127.0.0.1:{port}'
    try:
        _wait_http(url + '/health', timeout=180, proc=proc)
        lb, base, reg = _make_lb([url], monkeypatch, SKYT_QOS='1')
        spec = workload.WorkloadSpec(
            seed=7, duration_s=16.0, rate_rps=1.5, arrival='poisson',
            flash_at_s=6.0, flash_factor=20.0, flash_duration_s=4.0,
            tenants=(
                workload.TenantProfile(
                    tenant='clicky', cls='interactive', weight=1.0,
                    prompt_mean=3.0, prompt_sigma=0.3, prompt_cap=6,
                    output_mean=3.0, output_sigma=0.3, output_cap=4,
                    session_pool=2, session_reuse=0.5, prefix_len=2),
                workload.TenantProfile(
                    tenant='cruncher', cls='batch', weight=3.0,
                    prompt_mean=4.0, prompt_sigma=0.3, prompt_cap=8,
                    output_mean=40.0, output_sigma=0.5, output_cap=48,
                    session_pool=2, session_reuse=0.2, prefix_len=2)))
        sched = workload.generate_schedule(spec)
        # The drill is replayable: same spec, byte-identical schedule.
        assert workload.schedule_digest(sched) == \
            workload.schedule_digest(workload.generate_schedule(spec))
        runner = workload.OpenLoopRunner(
            workload.http_submitter(base, timeout_s=120.0),
            compression=2.0)
        outcomes = runner.run(sched)
        summary = workload.summarize(outcomes, compression=2.0)
        inter = summary['classes']['interactive']
        batch = summary['classes']['batch']
        # Protected class: zero 429/5xx/transport errors through a
        # 20x step the 2-slot replica cannot possibly serve in full.
        assert inter['shed'] == 0, summary
        assert inter['errors_5xx'] == 0, summary
        assert inter['transport_errors'] == 0, summary
        assert inter['ok'] == inter['offered'], summary
        # Sheddable class absorbed the crowd — sheds happened, inside
        # the flash window, and never as a 5xx.
        assert batch['shed'] > 0, summary
        assert any(o.status == 429 and 6.0 <= o.arrival.t < 10.0
                   for o in outcomes), summary
        assert batch['errors_5xx'] == 0, summary
        text = requests.get(url + '/metrics', timeout=5).text
        assert 'skyt_qos_shed_total{class="batch"' in text
        assert 'skyt_qos_shed_total{class="interactive"' not in text
        # The busy ledger attributed the drill's engine time to both
        # (class, tenant, model) slices — the cost half of the plane.
        led = requests.get(url + '/stats',
                           timeout=5).json()['capacity_ledger']
        attr = led['attributed_seconds']
        assert 'interactive/clicky/debug' in attr or \
            any(k.startswith('interactive/clicky/') for k in attr), led
        assert any(k.startswith('batch/cruncher/') for k in attr), led
        assert sum(attr.values()) <= led['busy_seconds'] + 1e-6
        # Recovery: once the crowd passes and the hold expires, BOTH
        # classes serve again (batch included).
        sess = requests.Session()
        for cls in ('interactive', 'batch'):
            deadline = time.time() + 60
            status = None
            while time.time() < deadline:
                r = sess.post(base + '/generate',
                              json={'tokens': [2, 3, 4],
                                    'max_tokens': 4},
                              headers={'X-Priority': cls,
                                       'X-Tenant': 'probe'},
                              timeout=60)
                status = r.status_code
                if status == 200:
                    break
                time.sleep(0.5)
            assert status == 200, \
                f'{cls} did not recover after the flash crowd'
        observed = reg.counter('skyt_lb_qos_sheds_observed_total', '',
                               ('lb', 'class'))
        assert observed.value(lb.lb_id, 'interactive') == 0
    finally:
        if proc.poll() is None:
            proc.kill()


# ========================================== preemption-safe training exit
@pytest.mark.integration
def test_sft_preemption_checkpoint_and_resume(tmp_path):
    """SIGTERM mid-run: sft checkpoints at the next step boundary,
    waits for the async save, and exits EXIT_CODE_PREEMPTED; a rerun
    resumes from that step instead of step 0."""
    from skypilot_tpu.runtime.job_lib import EXIT_CODE_PREEMPTED
    ckpt_dir = tmp_path / 'ckpt'
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # The persistent XLA compile cache (conftest exports it) wedges or
    # heap-corrupts the RESUME subprocess on this jax 0.4.37 CPU image
    # (cpu_aot_loader deserialization; reproduced outside pytest with
    # the cache on, never with it off). Pay the ~10s recompile instead.
    env.pop('JAX_COMPILATION_CACHE_DIR', None)
    env.pop('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', None)
    args = [sys.executable, '-m', 'skypilot_tpu.train.sft',
            '--model', 'debug', '--steps', '100000',
            '--batch', '1', '--seq', '16',
            '--checkpoint-dir', str(ckpt_dir),
            '--checkpoint-every', '5', '--log-every', '5']
    proc = subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        # Wait until at least one periodic checkpoint landed.
        deadline = time.time() + 300
        while time.time() < deadline:
            if proc.poll() is not None:
                out = proc.stdout.read()
                raise AssertionError(
                    f'sft died early rc={proc.returncode}:\n{out[-2000:]}')
            steps = [int(p.name) for p in ckpt_dir.glob('[0-9]*')
                     if p.name.isdigit()]
            if steps:
                break
            time.sleep(0.5)
        else:
            raise AssertionError('no checkpoint appeared')
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == EXIT_CODE_PREEMPTED, out[-2000:]
        assert 'preemption requested' in out
        saved_steps = sorted(int(p.name) for p in ckpt_dir.glob('[0-9]*')
                             if p.name.isdigit())
        assert saved_steps, out[-2000:]
        resume_at = saved_steps[-1]

        # Resume run: must start from the preemption checkpoint.
        args2 = list(args)
        args2[args2.index('--steps') + 1] = str(resume_at + 3)
        out2 = subprocess.run(args2, env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=300, check=True).stdout
        assert f'resumed from step {resume_at}' in out2
    finally:
        if proc.poll() is None:
            proc.kill()


def test_preempted_exit_code_maps_to_preempted_status(tmp_path,
                                                      monkeypatch):
    """runtime layer: a gang rank exiting EXIT_CODE_PREEMPTED is not a
    failure — the job lands in PREEMPTED (which the managed-jobs
    controller recovers) instead of FAILED."""
    monkeypatch.setenv('SKYT_AGENT_HOME', str(tmp_path))
    from skypilot_tpu.runtime import job_lib
    jid = job_lib.add_job('prejob', {'num_nodes': 2})
    job_lib.gang_mark(jid, 0, 'DONE', 0)
    job_lib.gang_mark(jid, 1, 'DONE', job_lib.EXIT_CODE_PREEMPTED)
    assert not job_lib.gang_any_failed(jid)
    assert job_lib.gang_any_preempted(jid)
    assert job_lib.gang_all_done(jid)
    # A real nonzero exit still reads as failure.
    job_lib.gang_mark(jid, 0, 'DONE', 1)
    assert job_lib.gang_any_failed(jid)


def test_preempted_wins_over_collateral_rank_failure(tmp_path,
                                                     monkeypatch):
    """Report-ordering race: when a preemption SIGTERMs the gang, the
    non-signalled ranks' collectives abort with real nonzero codes and
    usually report FIRST. The later rc=75 must still flip the job to
    PREEMPTED (the recovery signal), whichever order reports land."""
    monkeypatch.setenv('SKYT_AGENT_HOME', str(tmp_path))
    from skypilot_tpu.runtime import job_lib
    from skypilot_tpu.runtime import server as rt_server
    head = rt_server.HeadState(rt_server.ClusterConfig(
        {'cluster_name': 'c', 'num_nodes': 2,
         'ips': ['127.0.0.1', '127.0.0.2']}))
    # Order A: collateral failure first, cooperative exit second.
    jid = head.submit({'name': 'j1', 'run': 'x', 'num_nodes': 2})
    head.report(jid, 1, 'done', 1)
    assert job_lib.get_job(jid)['status'] is job_lib.JobStatus.FAILED
    head.report(jid, 0, 'done', job_lib.EXIT_CODE_PREEMPTED)
    assert job_lib.get_job(jid)['status'] is \
        job_lib.JobStatus.PREEMPTED
    # Order B: cooperative exit first; a later collateral failure must
    # not downgrade PREEMPTED back to FAILED.
    jid2 = head.submit({'name': 'j2', 'run': 'x', 'num_nodes': 2})
    head.report(jid2, 0, 'done', job_lib.EXIT_CODE_PREEMPTED)
    head.report(jid2, 1, 'done', 1)
    assert job_lib.get_job(jid2)['status'] is \
        job_lib.JobStatus.PREEMPTED
    # No 75 anywhere: plain failure, no recovery.
    jid3 = head.submit({'name': 'j3', 'run': 'x', 'num_nodes': 2})
    head.report(jid3, 0, 'done', 1)
    head.report(jid3, 1, 'done', 0)
    assert job_lib.get_job(jid3)['status'] is job_lib.JobStatus.FAILED


# ===================================== gang hang watchdog recovery drill
@pytest.mark.integration
def test_chaos_gang_hang_watchdog_recovery(tmp_path, tmp_state_dir,
                                           monkeypatch):
    """THE training-plane acceptance drill (docs/observability.md
    "Training plane"): one rank of a REAL 2-rank gang wedges via
    SKYT_FAULTS=train.step=hang -> the head agent's gang watchdog
    confirms the hang and escalates the cluster job to HUNG -> every
    rank has dumped a postmortem bundle (the hung rank via its
    sentinel, the survivor via the SIGTERM guard) -> the managed-jobs
    controller recovers (kill gang, relaunch) -> sft RESUMES from its
    preemption-era checkpoint -> SUCCEEDED, zero manual intervention.
    """
    import json
    import pathlib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import state
    from skypilot_tpu.jobs import core as jobs_core
    from skypilot_tpu.jobs import state as jobs_state
    from skypilot_tpu.train import postmortem as postmortem_lib

    drill = tmp_path / 'drill'
    drill.mkdir()
    pm_dir = tmp_path / 'postmortems'   # durable across the relaunch
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))
    monkeypatch.setenv('SKYT_JOBS_CHECK_GAP', '0.3')
    monkeypatch.setenv('SKYT_JOBS_PREEMPTION_GRACE', '1')
    # Fast watchdog thresholds (agents inherit this env at provision).
    monkeypatch.setenv('SKYT_WATCHDOG_MIN_S', '3')
    monkeypatch.setenv('SKYT_WATCHDOG_FACTOR', '2')
    monkeypatch.setenv('SKYT_WATCHDOG_CONFIRM', '2')
    monkeypatch.setenv('SKYT_WATCHDOG_INTERVAL_S', '0.5')
    monkeypatch.setenv('SKYT_WATCHDOG_POLL_S', '0.3')
    monkeypatch.setenv('SKYT_HEARTBEAT_INTERVAL_S', '0.1')
    # The persistent XLA compile cache wedges sft RESUME subprocesses
    # on this jax 0.4.37 CPU image (documented since PR 4) — the
    # relaunched ranks pay the recompile instead.
    monkeypatch.delenv('JAX_COMPILATION_CACHE_DIR', raising=False)
    monkeypatch.delenv('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS',
                       raising=False)
    state.reset_db_for_testing()
    jobs_state.reset_db_for_testing()

    # Rank 1 arms the hang fault ONCE (marker-guarded, so the
    # recovered incarnation runs clean); a small latency fault on
    # every step keeps rank 0 running long enough to be SIGTERM'd by
    # the HUNG kill (exercising its preempt-bundle path). The JAX
    # coordinator triplet is cleared: on the CPU backend each rank is
    # its own single-process jax runtime (multiprocess CPU collectives
    # are unimplemented in jax 0.4.x — the watchdog plane is what is
    # under test).
    run_cmd = f'''
RANK="$SKYT_NODE_RANK"
if [ "$RANK" = "1" ] && [ ! -f "{drill}/armed" ]; then
  touch "{drill}/armed"
  export SKYT_FAULTS="$SKYT_FAULTS;train.step=hang,arg=600,after=4"
fi
env SKYT_NUM_NODES=1 JAX_COORDINATOR_ADDRESS= JAX_NUM_PROCESSES= \\
    JAX_PROCESS_ID= \\
  {sys.executable} -m skypilot_tpu.train.sft --model debug \\
  --steps 120 --batch 1 --seq 16 --prefetch 0 \\
  --checkpoint-dir "{drill}/ckpt/rank-$RANK" --checkpoint-every 2 \\
  --log-every 10 2>&1 | tee -a "{drill}/rank-$RANK.out"
exit "${{PIPESTATUS[0]}}"
'''
    t = sky.Task(name='hangdrill', run=run_cmd, num_nodes=2,
                 envs={'SKYT_POSTMORTEM_DIR': str(pm_dir),
                       'SKYT_FAULTS': 'train.step=latency,arg=0.1',
                       'JAX_PLATFORMS': 'cpu'})
    t.set_resources(resources_lib.Resources(cloud='local'))

    jid = jobs_core.launch(t, retry_until_up=False)
    saw_recovering = False
    deadline = time.time() + 900
    job = None
    try:
        while time.time() < deadline:
            job = jobs_state.get_job(jid)
            if job['status'] == jobs_state.ManagedJobStatus.RECOVERING:
                saw_recovering = True
            if job['status'].is_terminal():
                break
            time.sleep(0.1)
        else:
            pytest.fail(f'drill never finished: {job}')

        out1 = (drill / 'rank-1.out').read_text() \
            if (drill / 'rank-1.out').exists() else ''
        assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED, \
            (job, out1[-2000:])
        assert job['recovery_count'] >= 1
        assert saw_recovering

        # Bundles from EVERY rank, durable across the relaunch: the
        # hung rank's sentinel bundle plus the survivor's SIGTERM
        # (preempt) bundle — each with stacks + spans + train state.
        bundles = postmortem_lib.list_bundles(root=str(pm_dir))
        reasons = {(b.get('rank'), b.get('reason')) for b in bundles}
        assert (1, 'hang') in reasons, bundles
        assert (0, 'preempt') in reasons, bundles
        for b in bundles:
            assert {'stacks.txt', 'spans.json', 'state.json'} <= \
                set(b['files']), b
        hang_state = json.loads(
            (pathlib.Path(next(
                b['path'] for b in bundles
                if (b.get('rank'), b.get('reason')) == (1, 'hang')))
             / 'state.json').read_text())
        assert hang_state['heartbeat']['stall']['stalled'] is True

        # The recovered rank resumed from its pre-hang checkpoint
        # (resume-from-step-k, not step 0).
        assert 'resumed from step' in out1, out1[-2000:]
    finally:
        for j in jobs_state.get_jobs():
            if not j['status'].is_terminal():
                try:
                    jobs_core.cancel([j['job_id']])
                except Exception:  # pylint: disable=broad-except
                    pass
        t_end = time.time() + 30
        while time.time() < t_end and any(
                not j['status'].is_terminal()
                for j in jobs_state.get_jobs()):
            time.sleep(0.5)
        for rec in state.get_clusters():
            try:
                from skypilot_tpu import core as sky_core
                sky_core.down(rec['name'], purge=True)
            except Exception:  # pylint: disable=broad-except
                pass
        state.reset_db_for_testing()
        jobs_state.reset_db_for_testing()


# ===================================== zero-downtime rolling updates
def _save_debug_checkpoints(tmp_path, seeds=(0, 7, 11)):
    """HF-format debug-model checkpoints (one per seed) the engine
    server's swap loader can read."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import llama
    from skypilot_tpu.models import weights as weights_lib
    cfg = _dc.replace(llama.CONFIGS['debug'], max_seq_len=64,
                      param_dtype='float32', dtype='float32')
    model = llama.LlamaModel(cfg)
    zeros = jnp.zeros((1, 8), jnp.int32)
    out = []
    for i, seed in enumerate(seeds):
        params = jax.jit(model.init)(jax.random.PRNGKey(seed), zeros)
        path = str(tmp_path / f'ckpt_{chr(ord("a") + i)}')
        weights_lib.save_hf_checkpoint(cfg, params, path)
        out.append(path)
    return out


_ENGINE_REPLICA = (
    'python -m skypilot_tpu.infer.server --model debug '
    '--port "$SKYT_REPLICA_PORT" --num-slots 2 --max-seq-len 64')


def _wait_rollout_phase(cport, token, phases, timeout=180):
    headers = {'Authorization': f'Bearer {token}'}
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            last = requests.get(
                f'http://127.0.0.1:{cport}/controller/status',
                headers=headers, timeout=10).json()
            ro = last.get('rollout') or {}
            if ro.get('phase') in phases:
                return last
        except requests.RequestException:
            pass
        time.sleep(0.3)
    raise AssertionError(
        f'rollout never reached {phases}: '
        f'{(last or {}).get("rollout")}')


@pytest.mark.integration
def test_chaos_rolling_update_canary_rollback(control_plane_env,
                                              monkeypatch):
    """THE zero-downtime-rollout drill (docs/robustness.md
    "Zero-downtime rollouts", validation step 15): 2 REAL engine
    replicas behind the real controller + an in-process LB.

    Run 1 (unfaulted): a mid-burst rolling update to checkpoint B
    lands the new weight version fleet-wide — zero client-visible
    5xx, zero relaunches (the launch counter never ticks past the
    initial 2), every replica at weight_version 2.

    Run 2 (faulted): `weights.swap=error` armed on checkpoint C — the
    canary's swap aborts with its old weights intact, the rollout
    auto-rolls-back, the mid-burst traffic still sees zero 5xx, and
    the fleet ends on the OLD version with the spec uncommitted."""
    import yaml as yaml_lib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.train import push_weights

    tmp_path = control_plane_env
    ckpt_a, ckpt_b, ckpt_c = _save_debug_checkpoints(tmp_path)
    # Arm the canary-kill for run 2 ONLY: the where= filter keys on
    # the pushed checkpoint, so run 1 (ckpt_b) is untouched. The env
    # is inherited by the replica processes at launch.
    monkeypatch.setenv('SKYT_FAULTS',
                       f'weights.swap=error,where=checkpoint:{ckpt_c}')
    monkeypatch.setenv('SKYT_ROLLOUT_BAKE_S', '0.5')
    task = sky.Task(name='rsvc', run=_ENGINE_REPLICA)
    task.set_resources(resources_lib.Resources(cloud='local'))
    spec = spec_lib.ServiceSpec(
        readiness_path='/health', min_replicas=2,
        initial_delay_seconds=600, probe_timeout_seconds=5,
        weights=ckpt_a)
    task.service = spec
    task_yaml = str(tmp_path / 'rsvc.task.yaml')
    with open(task_yaml, 'w', encoding='utf-8') as f:
        yaml_lib.safe_dump(task.to_yaml_config(), f)
    cport, lport = _free_port(), _free_port()
    assert serve_state.add_service('rsvc', spec, task_yaml, cport,
                                   lport)
    token = serve_state.get_service('rsvc')['auth_token']
    headers = {'Authorization': f'Bearer {token}'}
    curl = f'http://127.0.0.1:{cport}'

    ctrl = _spawn_service('rsvc', 'controller')
    lb = None
    try:
        _wait_replicas_ready('rsvc', 2, timeout=420)
        reg = metrics_lib.MetricsRegistry()
        lb_port = _free_port()
        lb = lb_lib.SkyServeLoadBalancer(
            curl, lb_port, controller_auth=token,
            metrics_registry=reg)
        _run_app_bg(lb.make_app(), lb_port)
        base = f'http://127.0.0.1:{lb_port}'
        deadline = time.time() + 120
        while time.time() < deadline and \
                len(lb.policy.ready_replicas) < 2:
            time.sleep(0.2)
        assert len(lb.policy.ready_replicas) == 2

        results = []
        stop_burst = threading.Event()
        lock = threading.Lock()

        def burst():
            i = 0
            while not stop_burst.is_set():
                i += 1
                try:
                    r = requests.post(
                        base + '/generate',
                        json={'tokens': [1 + (i % 5), 2, 3],
                              'max_tokens': 6},
                        timeout=120)
                    code = r.status_code
                except requests.RequestException as e:
                    code = f'EXC:{e!r}'
                with lock:
                    results.append(code)

        threads = [threading.Thread(target=burst) for _ in range(3)]
        for th in threads:
            th.start()
        try:
            # ---- run 1: clean rolling update, driven through the
            # real weight-push client (train/push_weights.py).
            state = push_weights.push(curl, ckpt_b, token=token,
                                      wait=True, timeout_s=300)
            assert state['phase'] == 'done'
        finally:
            time.sleep(1.0)     # a little post-rollout traffic
            stop_burst.set()
            for th in threads:
                th.join(timeout=120)
        with lock:
            run1 = list(results)
        assert run1 and all(c == 200 for c in run1), run1[:20]
        status = requests.get(curl + '/controller/status',
                              headers=headers, timeout=10).json()
        assert all(r['weight_version'] == 2 and r['version'] == 2
                   for r in status['replicas']), status['replicas']
        # Zero relaunches: the launch counter holds at the initial 2.
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert 'skyt_serve_replica_launches_total{service="rsvc"} 2' \
            in mtext, mtext
        # The LB saw the new version through the sync.
        deadline = time.time() + 30
        while time.time() < deadline and \
                set(lb.state.replica_weight_version.values()) != {2}:
            time.sleep(0.3)
        assert set(lb.state.replica_weight_version.values()) == {2}

        # ---- run 2: the armed fault kills the canary's swap.
        results.clear()
        stop_burst.clear()
        threads = [threading.Thread(target=burst) for _ in range(3)]
        for th in threads:
            th.start()
        try:
            resp = requests.post(curl + '/controller/rolling_update',
                                 json={'checkpoint': ckpt_c},
                                 headers=headers, timeout=30)
            assert resp.status_code == 200, resp.text
            status = _wait_rollout_phase(cport, token,
                                         ('rolled_back',),
                                         timeout=240)
        finally:
            time.sleep(1.0)
            stop_burst.set()
            for th in threads:
                th.join(timeout=120)
        with lock:
            run2 = list(results)
        assert run2 and all(c == 200 for c in run2), run2[:20]
        ro = status['rollout']
        assert ro['phase'] == 'rolled_back'
        assert 'swap failed' in (ro['error'] or '')
        # Fleet ends on the OLD version; spec never committed.
        assert all(r['weight_version'] == 2 and r['version'] == 2
                   for r in status['replicas']), status['replicas']
        assert serve_state.get_service('rsvc')['version'] == 2
        # Still zero relaunches across BOTH runs.
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert 'skyt_serve_replica_launches_total{service="rsvc"} 2' \
            in mtext, mtext
        assert ('skyt_serve_rollouts_total{service="rsvc",'
                'outcome="done"} 1') in mtext
        assert ('skyt_serve_rollouts_total{service="rsvc",'
                'outcome="rolled_back"} 1') in mtext
    finally:
        if ctrl.poll() is None:
            try:
                requests.post(curl + '/controller/terminate', json={},
                              headers=headers, timeout=60)
            except requests.RequestException:
                pass
            ctrl.kill()
        del lb


def _wait_adapter_phase(cport, token, phases, timeout=240):
    headers = {'Authorization': f'Bearer {token}'}
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            last = requests.get(
                f'http://127.0.0.1:{cport}/controller/status',
                headers=headers, timeout=10).json()
            au = last.get('adapter_update') or {}
            if au.get('phase') in phases:
                return last
        except requests.RequestException:
            pass
        time.sleep(0.3)
    raise AssertionError(
        f'adapter update never reached {phases}: '
        f'{(last or {}).get("adapter_update")}')


def _save_debug_adapter(tmp_path, rank=2, alpha=4.0, seed=9):
    """An Orbax adapter dir shaped exactly like an `sft --lora-rank`
    run writes (TrainStateS), for the debug model the drill's
    replicas serve."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np
    import flax.linen as nn

    from skypilot_tpu.models import llama
    from skypilot_tpu.train import checkpoint as ckpt_lib
    from skypilot_tpu.train import lora as tlora
    from skypilot_tpu.train import trainer

    cfg = _dc.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))['params'])
    lcfg = tlora.LoRAConfig(rank=rank, alpha=alpha)
    tree = tlora.init_lora_params(params, lcfg,
                                  jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    tree = jax.tree.map(
        lambda x: jnp.asarray(rng.normal(0, 0.1, x.shape), x.dtype),
        tree)
    tx = trainer.make_optimizer(trainer.TrainerConfig())
    state = trainer.TrainStateS(step=jnp.zeros((), jnp.int32),
                                params=tree, opt_state=tx.init(tree))
    path = str(tmp_path / 'adapter_fr')
    ck = ckpt_lib.Checkpointer(path, async_save=False)
    ck.save(0, state, force=True)
    ck.wait()
    ck.close()
    return path


@pytest.mark.integration
def test_chaos_adapter_hot_load_drill(control_plane_env):
    """THE adapter hot-load drill (docs/serving.md "Adapter fleet",
    validation step 21): 2 REAL engine replicas behind the real
    controller + an in-process LB. A fleet-wide adapter load lands
    mid-burst through POST /controller/adapters — zero client-visible
    5xx, zero relaunches — then the front door routes by model name
    (aggregated /v1/models, honest 404), a direct unload is REFUSED
    while requests reference the adapter, and the fleet-wide unload
    converges clean."""
    import yaml as yaml_lib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    tmp_path = control_plane_env
    adapter_dir = _save_debug_adapter(tmp_path)
    task = sky.Task(name='asvc', run=_ENGINE_REPLICA)
    task.set_resources(resources_lib.Resources(cloud='local'))
    spec = spec_lib.ServiceSpec(
        readiness_path='/health', min_replicas=2,
        initial_delay_seconds=600, probe_timeout_seconds=5)
    task.service = spec
    task_yaml = str(tmp_path / 'asvc.task.yaml')
    with open(task_yaml, 'w', encoding='utf-8') as f:
        yaml_lib.safe_dump(task.to_yaml_config(), f)
    cport, lport = _free_port(), _free_port()
    assert serve_state.add_service('asvc', spec, task_yaml, cport,
                                   lport)
    token = serve_state.get_service('asvc')['auth_token']
    headers = {'Authorization': f'Bearer {token}'}
    curl = f'http://127.0.0.1:{cport}'

    ctrl = _spawn_service('asvc', 'controller')
    lb = None
    try:
        _wait_replicas_ready('asvc', 2, timeout=420)
        reg = metrics_lib.MetricsRegistry()
        lb_port = _free_port()
        lb = lb_lib.SkyServeLoadBalancer(
            curl, lb_port, controller_auth=token,
            metrics_registry=reg)
        _run_app_bg(lb.make_app(), lb_port)
        base = f'http://127.0.0.1:{lb_port}'
        deadline = time.time() + 120
        while time.time() < deadline and \
                len(lb.policy.ready_replicas) < 2:
            time.sleep(0.2)
        assert len(lb.policy.ready_replicas) == 2

        results = []
        stop_burst = threading.Event()
        lock = threading.Lock()

        def burst(lora=None):
            i = 0
            while not stop_burst.is_set():
                i += 1
                body = {'tokens': [1 + (i % 5), 2, 3],
                        'max_tokens': 6}
                if lora:
                    body['lora'] = lora
                try:
                    r = requests.post(base + '/generate', json=body,
                                      timeout=120)
                    code = r.status_code
                except requests.RequestException as e:
                    code = f'EXC:{e!r}'
                with lock:
                    results.append(code)

        threads = [threading.Thread(target=burst) for _ in range(3)]
        for th in threads:
            th.start()
        try:
            # ---- fleet-wide hot load, mid-burst.
            resp = requests.post(
                curl + '/controller/adapters',
                json={'op': 'load', 'name': 'fr',
                      'checkpoint': adapter_dir, 'alpha': 4.0},
                headers=headers, timeout=30)
            assert resp.status_code == 200, resp.text
            # A second update while one is active: 409, not a queue.
            resp2 = requests.post(
                curl + '/controller/adapters',
                json={'op': 'load', 'name': 'de',
                      'checkpoint': adapter_dir},
                headers=headers, timeout=30)
            assert resp2.status_code == 409, resp2.text
            status = _wait_adapter_phase(cport, token, ('done',))
        finally:
            time.sleep(1.0)     # a little post-load traffic
            stop_burst.set()
            for th in threads:
                th.join(timeout=120)
        with lock:
            run1 = list(results)
        assert run1 and all(c == 200 for c in run1), run1[:20]
        au = status['adapter_update']
        assert au['op'] == 'load' and au['name'] == 'fr'
        assert len(au['updated']) == 2, au
        # Zero relaunches: hot load never restarted a replica.
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert 'skyt_serve_replica_launches_total{service="asvc"} 2' \
            in mtext, mtext
        # The adapter set rides the sync into the LB's world view.
        deadline = time.time() + 60
        while time.time() < deadline and not (
                len(lb.state.replica_adapters) == 2 and
                all('fr' in named for named in
                    lb.state.replica_adapters.values())):
            time.sleep(0.3)
        assert all('fr' in named for named in
                   lb.state.replica_adapters.values()), \
            lb.state.replica_adapters

        # Front door model surface: aggregated /v1/models lists the
        # adapter fleet-wide (and teaches the LB the base id).
        models = requests.get(base + '/v1/models', timeout=30).json()
        by_id = {e['id']: e for e in models['data']}
        assert 'fr' in by_id and by_id['fr'].get('parent') == 'debug'
        assert by_id['fr'].get('replicas') == 2
        # Model-named request serves through the adapter...
        r = requests.post(base + '/v1/completions',
                          json={'model': 'fr', 'prompt': 'hi',
                                'max_tokens': 4}, timeout=120)
        assert r.status_code == 200, r.text
        # ...and a model NOBODY hosts is an honest front-door 404.
        r = requests.post(base + '/v1/completions',
                          json={'model': 'ghost', 'prompt': 'hi',
                                'max_tokens': 4}, timeout=120)
        assert r.status_code == 404, r.text
        assert r.json()['error']['code'] == 'model_not_found'

        # ---- unload-while-referenced: long adapter generations hold
        # the id in flight on a specific replica; its direct unload
        # must 409 with the stack untouched.
        cstat = requests.get(curl + '/controller/status',
                             headers=headers, timeout=10).json()
        endpoint = cstat['replicas'][0]['endpoint']
        long_results = []

        def long_gen():
            r = requests.post(
                endpoint + '/generate',
                json={'tokens': [1, 2, 3], 'max_tokens': 60,
                      'lora': 'fr'}, timeout=120)
            long_results.append(r.status_code)

        lthreads = [threading.Thread(target=long_gen)
                    for _ in range(6)]
        for th in lthreads:
            th.start()
        time.sleep(0.05)
        r = requests.post(endpoint + '/admin/adapters',
                          json={'op': 'unload', 'name': 'fr'},
                          headers=headers, timeout=30)
        assert r.status_code == 409, (r.status_code, r.text)
        assert 'referenced' in r.json()['error']
        for th in lthreads:
            th.join(timeout=120)
        assert long_results == [200] * 6, long_results

        # ---- fleet-wide unload converges clean once drained.
        resp = requests.post(curl + '/controller/adapters',
                             json={'op': 'unload', 'name': 'fr'},
                             headers=headers, timeout=30)
        assert resp.status_code == 200, resp.text
        _wait_adapter_phase(cport, token, ('done',))
        deadline = time.time() + 60
        while time.time() < deadline and any(
                'fr' in named for named in
                lb.state.replica_adapters.values()):
            time.sleep(0.3)
        assert not any('fr' in named for named in
                       lb.state.replica_adapters.values())
        # Both converges visible in the orchestrator counter; still
        # zero relaunches across the whole drill.
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert ('skyt_serve_adapter_updates_total{service="asvc",'
                'outcome="done"} 2') in mtext, mtext
        assert 'skyt_serve_replica_launches_total{service="asvc"} 2' \
            in mtext, mtext
    finally:
        if ctrl.poll() is None:
            try:
                requests.post(curl + '/controller/terminate', json={},
                              headers=headers, timeout=60)
            except requests.RequestException:
                pass
            ctrl.kill()
        del lb


_ADMIN_FAKE_REPLICA = (
    "python -c \""
    "import http.server, json, os;\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def _ok(self, body=b'ok'):\n"
    "        self.send_response(200); self.end_headers();\n"
    "        self.wfile.write(body)\n"
    "    def do_GET(self):\n"
    "        self._ok()\n"
    "    def do_POST(self):\n"
    "        n = int(self.headers.get('Content-Length') or 0);\n"
    "        self.rfile.read(n);\n"
    "        self._ok(json.dumps({'ok': True}).encode())\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYT_REPLICA_PORT'])), H).serve_forever()\"")


@pytest.mark.integration
def test_chaos_rollout_resume_after_controller_sigkill(
        control_plane_env, monkeypatch):
    """Controller SIGKILLed mid-BAKE: the restarted controller adopts
    both replicas (zero relaunches) AND recovers the persisted
    rollout — canary/bake observations died with the process, so it
    conservatively swaps the canary back and lands 'rolled_back' with
    the baseline spec intact."""
    import yaml as yaml_lib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    tmp_path = control_plane_env
    # A bake long enough that the kill lands inside it.
    monkeypatch.setenv('SKYT_ROLLOUT_BAKE_S', '600')
    task = sky.Task(name='rrsvc', run=_ADMIN_FAKE_REPLICA)
    task.set_resources(resources_lib.Resources(cloud='local'))
    spec = spec_lib.ServiceSpec(
        readiness_path='/', min_replicas=2, initial_delay_seconds=60,
        probe_timeout_seconds=2, weights=str(tmp_path / 'w1'))
    task.service = spec
    task_yaml = str(tmp_path / 'rrsvc.task.yaml')
    with open(task_yaml, 'w', encoding='utf-8') as f:
        yaml_lib.safe_dump(task.to_yaml_config(), f)
    cport = _free_port()
    assert serve_state.add_service('rrsvc', spec, task_yaml, cport,
                                   _free_port())
    token = serve_state.get_service('rrsvc')['auth_token']
    headers = {'Authorization': f'Bearer {token}'}
    curl = f'http://127.0.0.1:{cport}'

    ctrl = _spawn_service('rrsvc', 'controller')
    try:
        _wait_replicas_ready('rrsvc', 2)
        resp = requests.post(curl + '/controller/rolling_update',
                             json={'checkpoint': str(tmp_path / 'w2')},
                             headers=headers, timeout=30)
        assert resp.status_code == 200, resp.text
        _wait_rollout_phase(cport, token, ('bake',), timeout=60)
        # The chaos event: SIGKILL mid-bake, no cleanup of any kind.
        ctrl.kill()
        ctrl.wait(timeout=30)
        assert serve_state.get_rollout('rrsvc')['phase'] == 'bake'

        ctrl = _spawn_service('rrsvc', 'controller')
        status = _wait_rollout_phase(cport, token, ('rolled_back',),
                                     timeout=120)
        ro = status['rollout']
        assert 'restarted during bake' in ro['error']
        assert ro['updated'] == []
        # Adopted, not relaunched — and back on the baseline.
        assert all(r['weight_version'] == 1 and r['version'] == 1
                   for r in status['replicas']), status['replicas']
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert ('skyt_serve_replica_adoptions_total{service="rrsvc"} '
                '2') in mtext, mtext
        assert 'skyt_serve_replica_launches_total{service="rrsvc"}' \
            not in mtext, mtext
        assert serve_state.get_service('rrsvc')['version'] == 1
    finally:
        if ctrl.poll() is None:
            try:
                requests.post(curl + '/controller/terminate', json={},
                              headers=headers, timeout=60)
            except requests.RequestException:
                pass
            ctrl.kill()


@pytest.mark.integration
def test_chaos_kv_warm_restart_drill(monkeypatch):
    """Tiered-KV warm restart (docs/performance.md "Tiered prefix
    cache"): two SKYT_KV_TIER=fleet replica processes behind a
    prefix-affinity LB; the prefix's owner is SIGKILLed mid-burst
    (failover publishes the prefix on the survivor, zero 5xx), then
    relaunched on the same port. The relaunched replica warms from its
    peer over /kv/prefix — fleet-tier hits > 0 — and every burst's
    token stream is byte-identical to the pre-kill golden."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    kv_env = {'SKYT_KV_TIER': 'fleet', 'SKYT_ADMIN_TOKEN': 'kv-drill'}
    p1, p2 = _free_port(), _free_port()
    urls = [f'http://127.0.0.1:{p1}', f'http://127.0.0.1:{p2}']
    procs = {urls[0]: _spawn_replica(p1, kv_env, max_seq_len=128),
             urls[1]: _spawn_replica(p2, kv_env, max_seq_len=128)}
    # One shared 100-token prompt: its first 64-token page is the
    # prefix the fleet economy moves between replicas.
    prompt = [(j * 37) % 97 + 3 for j in range(100)]
    body = {'tokens': prompt, 'max_tokens': 8}
    try:
        for url in urls:
            _wait_http(url + '/health', timeout=300,
                       proc=procs[url])
        for k, v in (('SKYT_SERVE_LB_SYNC_INTERVAL', '3600'),
                     ('SKYT_LB_RETRY_BACKOFF_S', '0.02'),
                     ('SKYT_LB_BREAKER_THRESHOLD', '2'),
                     ('SKYT_LB_BREAKER_COOLDOWN_S', '1')):
            monkeypatch.setenv(k, v)
        lb_port = _free_port()
        lb = lb_lib.SkyServeLoadBalancer(
            'http://127.0.0.1:9', lb_port, policy='prefix_affinity',
            metrics_registry=metrics_lib.MetricsRegistry())
        lb.policy.set_ready_replicas(list(urls))
        _run_app_bg(lb.make_app(), lb_port)
        base = f'http://127.0.0.1:{lb_port}'
        _wait_http(base + '/metrics', timeout=30)

        def burst(n=4):
            out = []
            for _ in range(n):
                r = requests.post(base + '/generate', json=body,
                                  timeout=120)
                out.append((r.status_code,
                            r.headers.get('X-Replica-Id'),
                            tuple(r.json().get('tokens', ()))
                            if r.status_code == 200 else None))
            return out

        # Warm burst: the affinity ring homes every request on one
        # owner; later requests prefix-hit its published page.
        first = burst()
        assert all(code == 200 for code, _, _ in first), first
        owner = first[0][1]
        assert owner in urls and \
            all(rep == owner for _, rep, _ in first), first
        golden = first[0][2]
        assert len(golden) == 8
        assert all(toks == golden for _, _, toks in first), first
        survivor = urls[1 - urls.index(owner)]

        # Kill the owner MID-burst: concurrent requests fail over to
        # the survivor — zero client-visible 5xx, identical streams —
        # and the survivor now holds (and publishes) the prefix.
        results, lock = [], threading.Lock()

        def one():
            r = requests.post(base + '/generate', json=body,
                              timeout=120)
            with lock:
                results.append((r.status_code,
                                tuple(r.json().get('tokens', ()))
                                if r.status_code == 200 else None))

        threads = [threading.Thread(target=one) for _ in range(6)]
        for th in threads[:2]:
            th.start()
        procs[owner].kill()
        for th in threads[2:]:
            th.start()
        for th in threads:
            th.join(timeout=180)
        assert len(results) == 6
        assert all(code == 200 for code, _ in results), results
        assert all(toks == golden for _, toks in results), results

        # Relaunch the owner on ITS port (cold HBM, empty host store)
        # and let the breaker's cooldown lapse.
        procs[owner] = _spawn_replica(
            int(owner.rsplit(':', 1)[1]), kv_env, max_seq_len=128)
        _wait_http(owner + '/health', timeout=300, proc=procs[owner])
        time.sleep(1.2)

        # Re-burst: the ring still homes the key on the relaunched
        # owner; the LB's X-KV-Peer hint names the survivor and the
        # owner warms from it instead of recomputing.
        deadline = time.time() + 60
        warmed = None
        while time.time() < deadline:
            third = burst(2)
            assert all(code == 200 for code, _, _ in third), third
            assert all(toks == golden for _, _, toks in third), third
            stats = requests.get(owner + '/stats', timeout=30).json()
            warmed = stats.get('kv_tier')
            if warmed and warmed.get('fetched_pages', 0) > 0:
                break
            time.sleep(0.5)
        assert warmed and warmed['fetched_pages'] > 0, warmed
        assert warmed['promotions'] > 0, warmed
        served = requests.get(owner + '/stats', timeout=30).json()
        assert served['prefix_cache']['hit_pages'] > 0, served
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()


# ==================== elastic capacity: surge queue + reshard drills
def _surge_metrics(reg, lb):
    outcomes = reg.counter('skyt_lb_surge_requests_total', '',
                           ('lb', 'outcome'))
    depth = reg.gauge('skyt_lb_surge_queue_depth', '', ('lb',))
    return (lambda o: outcomes.value(lb.lb_id, o),
            lambda: depth.value(lb.lb_id))


def _wait_gauge(read, want, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if read() == want:
            return
        time.sleep(0.02)
    raise AssertionError(f'gauge never reached {want}: {read()}')


def test_lb_surge_queue_parks_then_serves(monkeypatch):
    """Scale-to-zero survival: with the ready set EMPTY a request
    parks in the surge queue (depth gauge ticks up) instead of
    eating the 503 — and is served the moment a replica appears."""
    lb, base, reg = _make_lb([], monkeypatch,
                             SKYT_LB_NO_REPLICA_POLL_S='0.05',
                             SKYT_LB_NO_REPLICA_TIMEOUT_S='30')
    outcome, depth = _surge_metrics(reg, lb)
    results = []

    def one():
        results.append(requests.get(base + '/g', timeout=30))

    th = threading.Thread(target=one)
    th.start()
    _wait_gauge(depth, 1)           # parked, not rejected
    url = _ok_replica('woke')
    lb.policy.set_ready_replicas([url])
    th.join(timeout=30)
    assert results and results[0].status_code == 200
    assert results[0].text == 'hello-woke'
    assert outcome('served') == 1
    assert outcome('overflow') == 0 and outcome('timeout') == 0
    _wait_gauge(depth, 0)


def test_lb_surge_queue_overflow_is_honest_503(monkeypatch):
    """At SKYT_LB_SURGE_QUEUE_MAX the queue answers 503 + Retry-After
    IMMEDIATELY (no park): a flash crowd against a scaled-to-zero
    fleet must not become a memory bomb plus timeouts."""
    lb, base, reg = _make_lb([], monkeypatch,
                             SKYT_LB_SURGE_QUEUE_MAX='2',
                             SKYT_LB_NO_REPLICA_POLL_S='0.05',
                             SKYT_LB_NO_REPLICA_TIMEOUT_S='30')
    outcome, depth = _surge_metrics(reg, lb)
    parked = []

    def one():
        parked.append(requests.get(base + '/g', timeout=30))

    threads = [threading.Thread(target=one) for _ in range(2)]
    for th in threads:
        th.start()
    _wait_gauge(depth, 2)
    t0 = time.time()
    r = requests.get(base + '/g', timeout=10)    # third: over cap
    assert r.status_code == 503
    assert time.time() - t0 < 3                  # immediate, no park
    assert float(r.headers['Retry-After']) >= 1.0
    assert outcome('overflow') == 1
    lb.policy.set_ready_replicas([_ok_replica()])
    for th in threads:
        th.join(timeout=30)
    assert [p.status_code for p in parked] == [200, 200]
    assert outcome('served') == 2


def test_lb_surge_queue_timeout_is_bounded(monkeypatch):
    """A parked request past the no-replica deadline gets an honest
    503 + Retry-After in bounded time — never a silent hang."""
    lb, base, reg = _make_lb([], monkeypatch,
                             SKYT_LB_NO_REPLICA_POLL_S='0.05',
                             SKYT_LB_NO_REPLICA_TIMEOUT_S='0.5')
    outcome, _depth = _surge_metrics(reg, lb)
    t0 = time.time()
    r = requests.get(base + '/g', timeout=10)
    elapsed = time.time() - t0
    assert r.status_code == 503
    assert elapsed < 5, elapsed
    assert float(r.headers['Retry-After']) >= 1.0
    assert outcome('timeout') == 1 and outcome('served') == 0


def test_chaos_flash_crowd_scaled_to_zero(monkeypatch):
    """THE flash-crowd-vs-scaled-to-zero drill (docs/robustness.md
    "Elastic capacity"): 8 simultaneous arrivals against an EMPTY
    ready set with a 4-deep surge queue. Exactly 4 park (the queue is
    deterministic: the LB's event loop admits serially); the 4
    overflows get an immediate honest 503 + Retry-After. When the
    fleet wakes, every parked request is served 200 — zero 5xx for
    the protected (parked) class across the cold start."""
    lb, base, reg = _make_lb([], monkeypatch,
                             SKYT_LB_SURGE_QUEUE_MAX='4',
                             SKYT_LB_NO_REPLICA_POLL_S='0.05',
                             SKYT_LB_NO_REPLICA_TIMEOUT_S='60')
    outcome, depth = _surge_metrics(reg, lb)
    results, lock = [], threading.Lock()

    def one():
        r = requests.get(base + '/g', timeout=60)
        with lock:
            results.append((r.status_code, r.headers.get('Retry-After')))

    threads = [threading.Thread(target=one) for _ in range(8)]
    for th in threads:
        th.start()
    # The crowd splits 4 parked / 4 overflowed before any wake.
    _wait_gauge(depth, 4, timeout=20)
    deadline = time.time() + 20
    while time.time() < deadline and outcome('overflow') < 4:
        time.sleep(0.05)
    assert outcome('overflow') == 4
    # Fleet wakes: one replica appears (controller sync, simulated).
    lb.policy.set_ready_replicas([_ok_replica('cold')])
    for th in threads:
        th.join(timeout=60)
    assert len(results) == 8
    served = [r for r in results if r[0] == 200]
    rejected = [r for r in results if r[0] == 503]
    assert len(served) == 4 and len(rejected) == 4, results
    # Every overflow carried an actionable Retry-After.
    assert all(ra is not None and float(ra) >= 1.0
               for _, ra in rejected), rejected
    assert outcome('served') == 4 and outcome('timeout') == 0
    _wait_gauge(depth, 0)


def _wait_reshard_phase(cport, token, phases, timeout=180):
    headers = {'Authorization': f'Bearer {token}'}
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            last = requests.get(
                f'http://127.0.0.1:{cport}/controller/status',
                headers=headers, timeout=10).json()
            rs = last.get('reshard') or {}
            if rs.get('phase') in phases:
                return last
        except requests.RequestException:
            pass
        time.sleep(0.3)
    raise AssertionError(
        f'reshard never reached {phases}: '
        f'{(last or {}).get("reshard")}')


@pytest.mark.integration
def test_chaos_reshard_rollback_and_controller_sigkill(
        control_plane_env, monkeypatch):
    """THE mid-reshard chaos drill (docs/robustness.md "Elastic
    capacity"): 2 REAL engine replicas behind the real controller +
    an in-process LB.

    Run 1 (clean): an in-place reshard 1 -> 2 virtual nodes lands
    fleet-wide mid-burst — zero client-visible 5xx, zero relaunches,
    weight_version untouched.

    Run 2 (faulted): `reshard=error` armed on target 4 — every
    replica refuses, the orchestrator rolls back automatically, the
    mid-burst traffic still sees zero 5xx and the fleet keeps the
    old layout.

    Run 3 (SIGKILL mid-reshard): the controller is SIGKILLed while a
    replica's reshard POST is in flight. Reshard state is in-memory
    BY DESIGN: the restarted controller adopts both replicas (zero
    relaunches), reports no reshard, the mixed-layout fleet keeps
    serving 200s, and re-issuing the reshard converges — the
    already-flipped replica no-ops (idempotent re-assert)."""
    import yaml as yaml_lib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    tmp_path = control_plane_env
    # where= keys on the reshard target, so each run picks its fault:
    # target 4 errors (run 2); target 1 stalls 2.5s (run 3's kill
    # window + the idempotent re-assert). Inherited by the replica
    # processes at launch.
    monkeypatch.setenv('SKYT_FAULTS',
                       'reshard=error,where=virtual_nodes:4;'
                       'reshard=latency,arg=2.5,where=virtual_nodes:1')
    monkeypatch.setenv('SKYT_ROLLOUT_RETRIES', '2')
    task = sky.Task(name='esvc', run=_ENGINE_REPLICA)
    task.set_resources(resources_lib.Resources(cloud='local'))
    spec = spec_lib.ServiceSpec(
        readiness_path='/health', min_replicas=2,
        initial_delay_seconds=600, probe_timeout_seconds=5)
    task.service = spec
    task_yaml = str(tmp_path / 'esvc.task.yaml')
    with open(task_yaml, 'w', encoding='utf-8') as f:
        yaml_lib.safe_dump(task.to_yaml_config(), f)
    cport, lport = _free_port(), _free_port()
    assert serve_state.add_service('esvc', spec, task_yaml, cport,
                                   lport)
    token = serve_state.get_service('esvc')['auth_token']
    headers = {'Authorization': f'Bearer {token}'}
    curl = f'http://127.0.0.1:{cport}'

    ctrl = _spawn_service('esvc', 'controller')
    lb = None
    try:
        _wait_replicas_ready('esvc', 2, timeout=420)
        reg = metrics_lib.MetricsRegistry()
        lb_port = _free_port()
        lb = lb_lib.SkyServeLoadBalancer(
            curl, lb_port, controller_auth=token,
            metrics_registry=reg)
        _run_app_bg(lb.make_app(), lb_port)
        base = f'http://127.0.0.1:{lb_port}'
        deadline = time.time() + 120
        while time.time() < deadline and \
                len(lb.policy.ready_replicas) < 2:
            time.sleep(0.2)
        assert len(lb.policy.ready_replicas) == 2

        def replica_stats():
            status = requests.get(curl + '/controller/status',
                                  headers=headers, timeout=10).json()
            out = {}
            for rep in status['replicas']:
                stats = requests.get(rep['endpoint'] + '/stats',
                                     timeout=30).json()
                out[rep['replica_id']] = (stats['virtual_nodes'],
                                          stats['weight_version'])
            return out

        assert set(replica_stats().values()) == {(1, 1)}

        results = []
        stop_burst = threading.Event()
        lock = threading.Lock()

        def burst():
            i = 0
            while not stop_burst.is_set():
                i += 1
                try:
                    r = requests.post(
                        base + '/generate',
                        json={'tokens': [1 + (i % 5), 2, 3],
                              'max_tokens': 6},
                        timeout=120)
                    code = r.status_code
                except requests.RequestException as e:
                    code = f'EXC:{e!r}'
                with lock:
                    results.append(code)

        def run_burst_during(fn):
            results.clear()
            stop_burst.clear()
            threads = [threading.Thread(target=burst)
                       for _ in range(2)]
            for th in threads:
                th.start()
            try:
                out = fn()
            finally:
                time.sleep(0.5)
                stop_burst.set()
                for th in threads:
                    th.join(timeout=120)
            with lock:
                codes = list(results)
            assert codes and all(c == 200 for c in codes), codes[:20]
            return out

        # ---- run 1: clean elastic flip 1 -> 2, mid-burst.
        def clean_flip():
            resp = requests.post(curl + '/controller/reshard',
                                 json={'virtual_nodes': 2},
                                 headers=headers, timeout=30)
            assert resp.status_code == 200, resp.text
            return _wait_reshard_phase(cport, token, ('done',),
                                       timeout=120)

        status = run_burst_during(clean_flip)
        assert status['reshard']['phase'] == 'done'
        # Layout flipped fleet-wide; the weights plane untouched.
        assert set(replica_stats().values()) == {(2, 1)}

        # ---- run 2: the armed fault refuses target 4 -> rollback.
        def faulted_flip():
            resp = requests.post(curl + '/controller/reshard',
                                 json={'virtual_nodes': 4},
                                 headers=headers, timeout=30)
            assert resp.status_code == 200, resp.text
            return _wait_reshard_phase(cport, token, ('rolled_back',),
                                       timeout=120)

        status = run_burst_during(faulted_flip)
        rs = status['reshard']
        assert rs['phase'] == 'rolled_back'
        assert 'replica' in (rs['error'] or '')
        # Old layout intact everywhere; still zero relaunches.
        assert set(replica_stats().values()) == {(2, 1)}
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert 'skyt_serve_replica_launches_total{service="esvc"} 2' \
            in mtext, mtext
        assert ('skyt_serve_reshards_total{service="esvc",'
                'outcome="done"} 1') in mtext
        assert ('skyt_serve_reshards_total{service="esvc",'
                'outcome="rolled_back"} 1') in mtext

        # ---- run 3: SIGKILL mid-reshard (target 1 stalls 2.5s per
        # replica call — the kill lands inside the first POST).
        resp = requests.post(curl + '/controller/reshard',
                             json={'virtual_nodes': 1},
                             headers=headers, timeout=30)
        assert resp.status_code == 200, resp.text
        _wait_reshard_phase(cport, token, ('reshard',), timeout=30)
        time.sleep(1.0)
        ctrl.kill()
        ctrl.wait(timeout=30)

        ctrl = _spawn_service('esvc', 'controller')
        _wait_replicas_ready('esvc', 2, timeout=120)
        deadline = time.time() + 60
        status = None
        while time.time() < deadline:
            try:
                status = requests.get(curl + '/controller/status',
                                      headers=headers,
                                      timeout=10).json()
                break
            except requests.RequestException:
                time.sleep(0.3)
        assert status is not None
        # In-memory by design: the restarted controller has no
        # reshard; the replicas were adopted, not relaunched.
        assert status['reshard'] is None
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert ('skyt_serve_replica_adoptions_total{service="esvc"} '
                '2') in mtext, mtext
        assert 'skyt_serve_replica_launches_total{service="esvc"}' \
            not in mtext, mtext
        # Mixed layouts are fine to serve: zero 5xx either way.
        for i in range(4):
            r = requests.post(base + '/generate',
                              json={'tokens': [2 + i, 3, 4],
                                    'max_tokens': 4},
                              timeout=120)
            assert r.status_code == 200, r.text
        # Re-issue: the operator's recovery lever. The already-
        # flipped replica no-ops; the straggler flips.
        resp = requests.post(curl + '/controller/reshard',
                             json={'virtual_nodes': 1},
                             headers=headers, timeout=30)
        assert resp.status_code == 200, resp.text
        _wait_reshard_phase(cport, token, ('done',), timeout=120)
        assert set(replica_stats().values()) == {(1, 1)}
    finally:
        if ctrl.poll() is None:
            try:
                requests.post(curl + '/controller/terminate', json={},
                              headers=headers, timeout=60)
            except requests.RequestException:
                pass
            ctrl.kill()
        del lb


@pytest.mark.integration
def test_chaos_scale_provision_latency_surge_honesty(
        control_plane_env, monkeypatch):
    """THE surge-honesty drill: provisioning of the only replica is
    stalled (`scale.provision=latency`) while a client arrives — the
    request parks in the surge queue and gets a BOUNDED honest
    503 + Retry-After (never a silent hang). Once the stalled launch
    completes, traffic serves and the cold start is attributed:
    skyt_serve_cold_starts_total{kind="wake_from_zero"} with
    cold-start seconds covering the stall."""
    import yaml as yaml_lib

    import skypilot_tpu as sky
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib

    tmp_path = control_plane_env
    monkeypatch.setenv('SKYT_FAULTS',
                       'scale.provision=latency,arg=6,count=1')
    monkeypatch.setenv('SKYT_LB_NO_REPLICA_TIMEOUT_S', '2')
    monkeypatch.setenv('SKYT_LB_NO_REPLICA_POLL_S', '0.1')
    task = sky.Task(name='zsvc', run=_ADMIN_FAKE_REPLICA)
    task.set_resources(resources_lib.Resources(cloud='local'))
    spec = spec_lib.ServiceSpec(
        readiness_path='/', min_replicas=1, initial_delay_seconds=60,
        probe_timeout_seconds=2)
    task.service = spec
    task_yaml = str(tmp_path / 'zsvc.task.yaml')
    with open(task_yaml, 'w', encoding='utf-8') as f:
        yaml_lib.safe_dump(task.to_yaml_config(), f)
    cport = _free_port()
    assert serve_state.add_service('zsvc', spec, task_yaml, cport,
                                   _free_port())
    token = serve_state.get_service('zsvc')['auth_token']
    headers = {'Authorization': f'Bearer {token}'}
    curl = f'http://127.0.0.1:{cport}'

    ctrl = _spawn_service('zsvc', 'controller')
    lb = None
    try:
        reg = metrics_lib.MetricsRegistry()
        lb_port = _free_port()
        lb = lb_lib.SkyServeLoadBalancer(
            curl, lb_port, controller_auth=token,
            metrics_registry=reg)
        _run_app_bg(lb.make_app(), lb_port)
        base = f'http://127.0.0.1:{lb_port}'
        _wait_http(base + '/metrics', timeout=30)
        outcome, _depth = _surge_metrics(reg, lb)

        # The flash arrival during the stalled provision: parked,
        # then honestly rejected within the bounded window.
        t0 = time.time()
        r = requests.get(base + '/g', timeout=20)
        elapsed = time.time() - t0
        assert r.status_code == 503, r.text
        assert elapsed < 10, elapsed          # bounded, not a hang
        assert float(r.headers['Retry-After']) >= 1.0
        assert outcome('timeout') == 1

        # The stalled launch eventually lands; the fleet wakes.
        _wait_replicas_ready('zsvc', 1, timeout=180)
        deadline = time.time() + 60
        while time.time() < deadline and not lb.policy.ready_replicas:
            time.sleep(0.2)
        assert lb.policy.ready_replicas
        r = requests.get(base + '/g', timeout=30)
        assert r.status_code == 200

        # Cold-start attribution: a wake-from-zero whose seconds
        # include the provisioning stall.
        mtext = requests.get(curl + '/controller/metrics',
                             headers=headers, timeout=10).text
        assert ('skyt_serve_cold_starts_total{service="zsvc",'
                'kind="wake_from_zero"} 1') in mtext, mtext
        m = re.search(r'skyt_serve_cold_start_seconds_total'
                      r'\{service="zsvc"\} ([0-9.e+-]+)', mtext)
        assert m is not None, mtext
        assert float(m.group(1)) >= 5.0, m.group(1)
    finally:
        if ctrl.poll() is None:
            try:
                requests.post(curl + '/controller/terminate', json={},
                              headers=headers, timeout=60)
            except requests.RequestException:
                pass
            ctrl.kill()
        del lb
