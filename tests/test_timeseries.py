"""utils/timeseries.py: ring eviction, counter-reset handling,
rate/quantile math under an injectable clock, series-cap drops, and
exposition parsing — the fleet telemetry plane's substrate."""
import math

from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import timeseries as ts_lib


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


def make_store(**kw):
    kw.setdefault('clock', FakeClock())
    return ts_lib.TimeSeriesStore(**kw)


# ------------------------------------------------------------- parsing
def test_parse_exposition_counters_gauges_and_types():
    text = (
        '# HELP x_total help text\n'
        '# TYPE x_total counter\n'
        'x_total{r="a",c="i"} 3\n'
        'x_total{r="b",c="i"} 4.5\n'
        '# TYPE g gauge\n'
        'g 0.25\n'
        'garbage line that is not a sample\n'
        'bad_value{x="y"} not-a-number\n')
    samples, types = ts_lib.parse_exposition(text)
    assert ('x_total', {'r': 'a', 'c': 'i'}, 3.0) in samples
    assert ('g', {}, 0.25) in samples
    assert len(samples) == 3            # malformed lines skipped
    assert types == {'x_total': 'counter', 'g': 'gauge'}


def test_parse_exposition_escapes_and_inf():
    text = ('h_bucket{le="+Inf",p="a\\"b\\\\c\\nd"} 7\n')
    samples, _ = ts_lib.parse_exposition(text)
    assert samples == [('h_bucket',
                        {'le': '+Inf', 'p': 'a"b\\c\nd'}, 7.0)]


def test_registry_roundtrip():
    """What utils/metrics renders, timeseries parses — the two halves
    of the plane must agree on the wire format."""
    reg = metrics_lib.MetricsRegistry()
    reg.counter('c_total', 'c', ('cls',)).labels('interactive').inc(5)
    reg.histogram('h_seconds', 'h', buckets=(0.1, 1.0)).observe(0.5)
    clock = FakeClock()
    store = ts_lib.TimeSeriesStore(clock=clock)
    n = store.scrape_registry(reg)
    assert n >= 5                       # counter + buckets + sum + count
    assert store.latest('c_total', {'cls': 'interactive'}) == (clock.t, 5.0)
    assert store.latest('h_seconds_count', {}) == (clock.t, 1.0)
    assert store.family_type('c_total') == 'counter'


# ------------------------------------------------------ rings and caps
def test_ring_eviction_keeps_newest():
    clock = FakeClock()
    store = make_store(max_points=3, clock=clock)
    for i in range(10):
        store.observe('g', {}, float(i), ts=clock.tick(1))
    pts = store.points('g', {})
    assert len(pts) == 3
    assert [v for _, v in pts] == [7.0, 8.0, 9.0]


def test_series_cap_drops_with_counter_and_keeps_serving():
    store = make_store(max_series=2)
    assert store.observe('a', {}, 1.0)
    assert store.observe('b', {}, 1.0)
    assert not store.observe('c', {}, 1.0)
    assert not store.observe('c', {}, 2.0)
    assert store.dropped_series == 2
    assert store.stats()['series'] == 2
    assert store.latest('a', {}) is not None
    assert store.latest('c', {}) is None
    # existing series still writable at the cap
    assert store.observe('a', {}, 2.0)


def test_prune_drops_stale_series():
    clock = FakeClock()
    store = make_store(clock=clock)
    store.observe('old', {}, 1.0, ts=clock.t)
    clock.tick(100)
    store.observe('new', {}, 1.0, ts=clock.t)
    assert store.prune(max_age_s=50) == 1
    assert store.latest('old', {}) is None
    assert store.latest('new', {}) is not None


# --------------------------------------------------------- delta / rate
def test_delta_and_rate_simple():
    clock = FakeClock()
    store = make_store(clock=clock)
    for v in (0, 10, 20, 30):
        store.observe('c_total', {}, v, ts=clock.tick(10))
    assert store.delta('c_total', {}, window_s=100) == 30
    assert store.rate('c_total', {}, window_s=100) == 1.0
    # window narrower than the data: only the in-window increase
    assert store.delta('c_total', {}, window_s=20) == 20


def test_counter_reset_handling():
    """A decrease = source restart: post-reset value counts as the
    post-reset increase (Prometheus increase() semantics)."""
    clock = FakeClock()
    store = make_store(clock=clock)
    for v in (0, 10, 5, 7):
        store.observe('c_total', {}, v, ts=clock.tick(10))
    assert store.delta('c_total', {}, window_s=100) == 10 + 5 + 2


def test_delta_none_without_enough_points():
    store = make_store()
    assert store.delta('c_total', {}, window_s=100) is None
    store.observe('c_total', {}, 5.0)
    assert store.delta('c_total', {}, window_s=100) is None


def test_sum_and_grouped_delta_across_labels():
    clock = FakeClock()
    store = make_store(clock=clock)
    for t in range(2):
        ts = clock.tick(10)
        store.observe('tok_total',
                      {'cls': 'interactive', 'tenant': 'a'},
                      10.0 * (t + 1), ts=ts)
        store.observe('tok_total',
                      {'cls': 'interactive', 'tenant': 'b'},
                      4.0 * (t + 1), ts=ts)
        store.observe('tok_total', {'cls': 'batch', 'tenant': 'a'},
                      100.0 * (t + 1), ts=ts)
    assert store.sum_delta('tok_total', {'cls': 'interactive'},
                           window_s=100) == 14.0
    assert store.sum_delta('tok_total', None, window_s=100) == 114.0
    assert store.sum_delta('tok_total', {'cls': 'nope'},
                           window_s=100) is None
    grouped = store.grouped_delta('tok_total', 'tenant', window_s=100,
                                  match={'cls': 'interactive'})
    assert grouped == {'a': 10.0, 'b': 4.0}


# ------------------------------------------------------------ quantiles
def _feed_hist(store, clock, deltas_by_le, labels=None, steps=2):
    """Feed cumulative bucket counters whose WINDOW increase per le is
    `deltas_by_le` (split across `steps` scrapes)."""
    labels = labels or {}
    cum = {le: 0.0 for le in deltas_by_le}
    ts = clock.tick(10)
    for le, c in cum.items():
        store.observe('h_bucket', {**labels, 'le': le}, c, ts=ts)
    for _ in range(steps):
        ts = clock.tick(10)
        for le in cum:
            cum[le] += deltas_by_le[le] / steps
            store.observe('h_bucket', {**labels, 'le': le}, cum[le],
                          ts=ts)


def test_windowed_quantile_interpolation():
    clock = FakeClock()
    store = make_store(clock=clock)
    # 10 obs <= 0.1, 10 more in (0.1, 1.0], none above.
    _feed_hist(store, clock,
               {'0.1': 10.0, '1': 20.0, '+Inf': 20.0})
    p50 = store.quantile('h', None, 0.5, window_s=100)
    assert math.isclose(p50, 0.1), p50
    p75 = store.quantile('h', None, 0.75, window_s=100)
    assert math.isclose(p75, 0.55), p75      # halfway into (0.1, 1.0]
    p100 = store.quantile('h', None, 1.0, window_s=100)
    assert math.isclose(p100, 1.0), p100


def test_quantile_merges_across_series():
    """Per-replica histograms merge: the fleet p95 is computed from the
    SUM of bucket increases, not an average of per-replica p95s."""
    clock = FakeClock()
    store = make_store(clock=clock)
    _feed_hist(store, clock, {'0.1': 10.0, '1': 10.0, '+Inf': 10.0},
               labels={'replica': '1'})
    _feed_hist(store, clock, {'0.1': 0.0, '1': 10.0, '+Inf': 10.0},
               labels={'replica': '2'})
    # 10 of 20 below 0.1 => p50 = 0.1; p95 interpolates in (0.1, 1].
    assert math.isclose(store.quantile('h', None, 0.5, window_s=1000),
                        0.1)
    p95 = store.quantile('h', None, 0.95, window_s=1000)
    assert 0.1 < p95 <= 1.0
    # match narrows to one replica
    assert math.isclose(
        store.quantile('h', {'replica': '2'}, 0.5, window_s=1000),
        0.55)


def test_quantile_none_when_empty_window():
    clock = FakeClock()
    store = make_store(clock=clock)
    _feed_hist(store, clock, {'0.1': 10.0, '+Inf': 10.0})
    clock.tick(10_000)
    assert store.quantile('h', None, 0.5, window_s=100) is None


# ------------------------------------------------------- re-exposition
def test_expose_latest_with_extra_labels():
    clock = FakeClock()
    store = make_store(clock=clock)
    store.scrape_text('# TYPE c_total counter\nc_total{cls="i"} 3\n')
    types: dict = {}
    lines = store.expose_latest(extra_labels={'replica': '7'},
                                types=types)
    assert lines == ['c_total{cls="i",replica="7"} 3']
    assert types == {'c_total': 'counter'}


def test_deterministic_under_fake_clock():
    """Same inputs + same clock => identical outputs (the property the
    SLO burn-rate tests lean on)."""
    def run():
        clock = FakeClock()
        store = make_store(clock=clock)
        for v in (0, 3, 9, 27):
            store.observe('c_total', {'cls': 'i'}, v,
                          ts=clock.tick(7))
        return (store.delta('c_total', {'cls': 'i'}, 100),
                store.rate('c_total', {'cls': 'i'}, 100),
                store.stats())
    assert run() == run()
