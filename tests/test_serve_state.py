"""serve.db durability contract (docs/robustness.md "Control plane"):
schema-version stamp, corrupt-DB fail-fast with a NAMED error (never a
silent relaunch-everything), the terminal-row prune sweep, and real
two-process WAL access — the controller and a standby LB share this
file concurrently and must never lose updates or crash on SQLITE_BUSY.
"""
import os
import pickle
import sqlite3
import subprocess
import sys
import time

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils import metrics as metrics_lib


@pytest.fixture()
def serve_db(tmp_state_dir):
    serve_state.reset_db_for_testing()
    yield os.path.join(str(tmp_state_dir), 'serve.db')
    serve_state.reset_db_for_testing()


def _spec():
    return spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)


def _replica(rid, status, terminal_at=None):
    return replica_managers.ReplicaInfo(
        replica_id=rid, cluster_name=f'svc-{rid}', version=1,
        status=status, terminal_at=terminal_at)


# ------------------------------------------------------------- schema stamp
def test_fresh_db_is_stamped_with_schema_version(serve_db):
    assert serve_state.add_service('svc', _spec(), '/t.yaml', 1, 2)
    with sqlite3.connect(serve_db) as conn:
        version = conn.execute('PRAGMA user_version').fetchone()[0]
    assert version == serve_state.SCHEMA_VERSION
    # WAL really is on (the concurrency contract for the standby LB).
    with sqlite3.connect(serve_db) as conn:
        mode = conn.execute('PRAGMA journal_mode').fetchone()[0]
    assert mode == 'wal'


def test_newer_schema_refused_with_named_error(serve_db):
    assert serve_state.add_service('svc', _spec(), '/t.yaml', 1, 2)
    serve_state.reset_db_for_testing()
    conn = sqlite3.connect(serve_db)
    conn.execute('PRAGMA user_version=99')
    conn.commit()
    conn.close()
    with pytest.raises(exceptions.ServeStateSchemaError) as err:
        serve_state.get_service('svc')
    assert 'v99' in str(err.value)


def test_corrupt_db_fails_fast_with_named_error(serve_db):
    assert serve_state.add_service('svc', _spec(), '/t.yaml', 1, 2)
    serve_state.reset_db_for_testing()
    with open(serve_db, 'wb') as f:
        f.write(b'this was never a sqlite file' * 64)
    with pytest.raises(exceptions.ServeStateCorruptError) as err:
        serve_state.get_services()
    # The error names the file — the disaster mode this guards against
    # is a restarting controller silently treating garbage as "no
    # replicas" and relaunching the world.
    assert serve_db in str(err.value)


def test_old_unstamped_db_is_migrated_not_refused(serve_db):
    """A v1 (pre-stamp, user_version=0) DB opens fine and comes out
    stamped — the stamp must never brick existing deployments."""
    assert serve_state.add_service('svc', _spec(), '/t.yaml', 1, 2)
    serve_state.reset_db_for_testing()
    conn = sqlite3.connect(serve_db)
    conn.execute('PRAGMA user_version=0')
    conn.commit()
    conn.close()
    assert serve_state.get_service('svc') is not None
    serve_state.reset_db_for_testing()
    with sqlite3.connect(serve_db) as conn:
        assert conn.execute('PRAGMA user_version').fetchone()[0] == \
            serve_state.SCHEMA_VERSION


# ------------------------------------------------------------- prune sweep
def test_prune_terminal_replicas_and_row_gauge(serve_db):
    del serve_db
    assert serve_state.add_service('svc', _spec(), '/t.yaml', 1, 2)
    now = time.time()
    serve_state.upsert_replica('svc', 1, _replica(
        1, serve_state.ReplicaStatus.READY))
    serve_state.upsert_replica('svc', 2, _replica(
        2, serve_state.ReplicaStatus.FAILED, terminal_at=now - 7200))
    serve_state.upsert_replica('svc', 3, _replica(
        3, serve_state.ReplicaStatus.FAILED, terminal_at=now - 10))
    serve_state.upsert_replica('svc', 4, _replica(
        4, serve_state.ReplicaStatus.PREEMPTED, terminal_at=now - 7200))
    assert serve_state.update_row_gauges()['replicas'] == 4

    pruned = serve_state.prune_terminal_replicas(older_than_s=3600)
    assert pruned == 2                      # old FAILED + old PREEMPTED
    left = {r.replica_id for r in serve_state.get_replicas('svc')}
    assert left == {1, 3}                   # live + recent-terminal stay
    gauge = metrics_lib.REGISTRY.gauge(
        'skyt_serve_state_rows', '', ('table',))
    assert gauge.value('replicas') == 2
    assert gauge.value('services') == 1

    # Unreadable pickles can never be adopted — pruned regardless of age.
    db = serve_state._get_db()  # pylint: disable=protected-access
    db.execute('INSERT INTO replicas VALUES (?, ?, ?)',
               ('svc', 9, b'not a pickle'))
    db.commit()
    assert serve_state.prune_terminal_replicas(older_than_s=3600) == 1
    assert {r.replica_id
            for r in serve_state.get_replicas('svc')} == {1, 3}


def test_prune_scopes_to_service_when_asked(serve_db):
    del serve_db
    for name in ('a', 'b'):
        assert serve_state.add_service(name, _spec(), '/t.yaml', 1, 2)
        serve_state.upsert_replica(name, 1, _replica(
            1, serve_state.ReplicaStatus.FAILED,
            terminal_at=time.time() - 7200))
    assert serve_state.prune_terminal_replicas(
        older_than_s=0, service_name='a') == 1
    assert serve_state.get_replicas('a') == []
    assert len(serve_state.get_replicas('b')) == 1


# ------------------------------------------- two-process WAL concurrency
_WRITER = r'''
import os, pickle, sys, time
sys.path.insert(0, {repo!r})
os.environ['SKYT_STATE_DIR'] = {state_dir!r}
from skypilot_tpu.serve import replica_managers, serve_state
start = float(sys.argv[1]); n = int(sys.argv[2]); base = int(sys.argv[3])
while time.time() < start:          # both processes start writing together
    time.sleep(0.005)
for i in range(n):
    rid = base + i
    serve_state.upsert_replica('cc-svc', rid,
        replica_managers.ReplicaInfo(
            replica_id=rid, cluster_name=f'cc-{{rid}}', version=1,
            status=serve_state.ReplicaStatus.READY))
    serve_state.set_service_status('cc-svc',
                                   serve_state.ServiceStatus.READY)
    got = serve_state.get_replicas('cc-svc')   # reader under writes
    assert any(r.replica_id == rid for r in got)
print('WRITER_OK', base)
'''


@pytest.mark.integration
def test_two_process_wal_writes_lose_nothing(serve_db, tmp_path):
    """The controller + standby-LB access pattern: two PROCESSES
    read/write serve.db simultaneously under WAL. Every row both sides
    wrote must land (no lost updates) and neither process may crash on
    lock contention — sqlite's busy handler (10s, sqlite_utils) plus
    WAL's single-writer queueing is the whole story; any 'database is
    locked' here is a recipe regression."""
    del tmp_path
    assert serve_state.add_service('cc-svc', _spec(), '/t.yaml', 1, 2)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    n = 40
    start = time.time() + 1.0
    script = _WRITER.format(repo=repo,
                            state_dir=os.environ['SKYT_STATE_DIR'])
    procs = [subprocess.Popen(
        [sys.executable, '-c', script, str(start), str(n), str(base)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for base in (1000, 2000)]
    # This process is a third concurrent writer (the CLI's role).
    while time.time() < start:
        time.sleep(0.005)
    for i in range(n):
        serve_state.upsert_replica('cc-svc', 3000 + i, _replica(
            3000 + i, serve_state.ReplicaStatus.READY))
    for proc in procs:
        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, out
        assert 'WRITER_OK' in out, out
    rows = {r.replica_id for r in serve_state.get_replicas('cc-svc')}
    want = set(range(1000, 1000 + n)) | set(range(2000, 2000 + n)) | \
        set(range(3000, 3000 + n))
    assert rows == want, f'lost updates: {sorted(want - rows)[:10]}'


def test_injected_sqlite_busy_is_absorbed_by_timeout(serve_db):
    """SQLITE_BUSY injection: a second connection holds the write lock
    (BEGIN IMMEDIATE) briefly while serve_state writes. The write must
    wait it out via the busy timeout and land — not raise 'database is
    locked' and not get lost."""
    assert serve_state.add_service('bsvc', _spec(), '/t.yaml', 1, 2)
    import threading
    blocker = sqlite3.connect(serve_db, timeout=5,
                              check_same_thread=False)
    blocker.execute('BEGIN IMMEDIATE')          # takes the write lock

    def release_soon():
        time.sleep(0.8)
        blocker.commit()
        blocker.close()

    th = threading.Thread(target=release_soon)
    th.start()
    t0 = time.time()
    serve_state.upsert_replica('bsvc', 1, _replica(
        1, serve_state.ReplicaStatus.READY))    # must block, then land
    waited = time.time() - t0
    th.join()
    assert waited >= 0.5, 'write did not actually contend'
    assert len(serve_state.get_replicas('bsvc')) == 1


def test_old_pickle_rows_backfill_new_fields(serve_db):
    """Rows written before the liveness-identity fields existed must
    restore with them backfilled (adoption logic relies on plain
    attribute access, not getattr guards)."""
    del serve_db
    assert serve_state.add_service('ovc', _spec(), '/t.yaml', 1, 2)
    info = _replica(1, serve_state.ReplicaStatus.READY)
    # Simulate the old on-disk shape by stripping the new attributes
    # from the pickled dict.
    state = dict(info.__dict__)
    for field in ('pid', 'pid_start', 'adopted_at', 'terminal_at',
                  'stats'):
        state.pop(field, None)
    old = replica_managers.ReplicaInfo.__new__(
        replica_managers.ReplicaInfo)
    old.__dict__.update(state)
    db = serve_state._get_db()  # pylint: disable=protected-access
    db.execute('INSERT INTO replicas VALUES (?, ?, ?)',
               ('ovc', 1, pickle.dumps(old)))
    db.commit()
    rows = serve_state.get_replicas('ovc')
    assert len(rows) == 1
    restored = replica_managers.backfill(rows[0])
    assert restored.pid is None and restored.terminal_at is None
    assert restored.stats is None


def test_unreadable_replica_row_is_skipped_not_fatal(serve_db):
    """A single garbage replica blob must not wedge the restarting
    controller or `serve status` (bare pickle.loads used to raise out
    of get_replicas) — it is skipped with a warning and left for the
    prune sweep to delete."""
    del serve_db
    assert serve_state.add_service('gvc', _spec(), '/t.yaml', 1, 2)
    serve_state.upsert_replica('gvc', 1, _replica(
        1, serve_state.ReplicaStatus.READY))
    db = serve_state._get_db()  # pylint: disable=protected-access
    db.execute('INSERT INTO replicas VALUES (?, ?, ?)',
               ('gvc', 2, b'\x80\x04 definitely not a ReplicaInfo'))
    db.commit()
    rows = serve_state.get_replicas('gvc')       # no raise
    assert [r.replica_id for r in rows] == [1]
    # The sweep reclaims the unreadable row.
    assert serve_state.prune_terminal_replicas(older_than_s=3600) == 1
    assert len(serve_state.get_replicas('gvc')) == 1
