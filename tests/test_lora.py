"""LoRA adapter tests (reference parity target:
llm/llama-3_1-finetuning/lora.yaml)."""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import lora as lora_lib
from skypilot_tpu.train import trainer

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


@pytest.fixture(scope='module')
def base():
    cfg = llama.CONFIGS['debug']
    model = llama.LlamaModel(cfg)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0),
                                    jnp.zeros((1, 16), jnp.int32))
    return cfg, model, nn.meta.unbox(variables['params'])


def test_init_targets_all_linears(base):
    cfg, model, params = base
    lcfg = lora_lib.LoRAConfig(rank=4)
    lora = lora_lib.init_lora_params(params, lcfg, jax.random.PRNGKey(1))
    leaves = jax.tree_util.tree_leaves_with_path(lora)
    # 7 targets x (a, b) on the scanned layer stack.
    assert len(leaves) == 14
    for path, leaf in leaves:
        keys = tuple(k.key for k in path
                     if isinstance(k, jax.tree_util.DictKey))
        assert keys[-1] in ('a', 'b')
        if keys[-1] == 'b':
            np.testing.assert_array_equal(np.asarray(leaf), 0.0)
        # scanned stack: leading layer axis preserved
        assert leaf.shape[0] == cfg.n_layers
        assert 4 in leaf.shape


def test_merge_identity_at_init(base):
    cfg, model, params = base
    lcfg = lora_lib.LoRAConfig(rank=4)
    lora = lora_lib.init_lora_params(params, lcfg, jax.random.PRNGKey(1))
    merged = lora_lib.merge_lora(params, lora, lcfg)
    toks = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)
    out_base = model.apply({'params': params}, toks)
    out_merged = model.apply({'params': merged}, toks)
    np.testing.assert_allclose(np.asarray(out_merged),
                               np.asarray(out_base), rtol=1e-6,
                               atol=1e-6)


def test_merge_changes_output_when_b_nonzero(base):
    cfg, model, params = base
    lcfg = lora_lib.LoRAConfig(rank=4)
    lora = lora_lib.init_lora_params(params, lcfg, jax.random.PRNGKey(1))
    lora = jax.tree.map(
        lambda x: x + 0.05, lora)  # push B off zero
    merged = lora_lib.merge_lora(params, lora, lcfg)
    toks = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)
    out_base = np.asarray(model.apply({'params': params}, toks))
    out_merged = np.asarray(model.apply({'params': merged}, toks))
    assert np.abs(out_merged - out_base).max() > 1e-4


def test_only_adapters_train(base):
    """Two LoRA steps: frozen base params bit-identical, adapter params
    move, loss finite, optimizer state shaped like the adapter tree."""
    cfg, model, params = base
    lcfg = lora_lib.LoRAConfig(rank=4)
    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec())  # single device
    tcfg = trainer.TrainerConfig(warmup_steps=1, total_steps=4,
                                 learning_rate=1e-2)
    tx = trainer.make_optimizer(tcfg)
    state = lora_lib.create_lora_state(model, params, tx, lcfg,
                                       jax.random.PRNGKey(1))
    assert (jax.tree_util.tree_structure(state.params) ==
            jax.tree_util.tree_structure(
                jax.tree.map(lambda x: x,
                             state.opt_state[1][0].mu)))

    frozen_before = jax.tree.map(lambda x: np.asarray(x).copy(), params)
    lora_before = jax.tree.map(lambda x: np.asarray(x).copy(),
                               state.params)
    step = lora_lib.make_lora_train_step(model, params, tx, mesh, lcfg)
    rng = np.random.default_rng(0)
    for _ in range(2):
        toks = rng.integers(0, cfg.vocab_size, (2, 17), dtype=np.int32)
        batch = {'tokens': jnp.asarray(toks[:, :-1]),
                 'targets': jnp.asarray(toks[:, 1:])}
        state, metrics = step(state, batch)
    assert np.isfinite(float(metrics['loss']))

    # Base params untouched.
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(frozen_before),
            jax.tree_util.tree_leaves_with_path(params)):
        np.testing.assert_array_equal(a, np.asarray(b), err_msg=str(pa))
    # Adapters moved.
    moved = [
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(jax.tree.leaves(lora_before),
                        jax.tree.leaves(state.params))]
    assert any(moved)


def test_num_lora_params_small(base):
    cfg, model, params = base
    lcfg = lora_lib.LoRAConfig(rank=4)
    lora = lora_lib.init_lora_params(params, lcfg, jax.random.PRNGKey(1))
    n_lora = lora_lib.num_lora_params(lora)
    n_base = sum(int(x.size) for x in jax.tree.leaves(params))
    assert n_lora < 0.2 * n_base


def test_finetune_export_serve_loop(tmp_path):
    """The full reference-recipe loop on debug shapes: real base
    checkpoint -> sft --lora-rank -> export_lora merge -> the merged
    HF dir serves through build_engine."""
    import dataclasses

    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import weights
    from skypilot_tpu.train import export_lora, sft

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(5),
                                 jnp.zeros((1, 8), jnp.int32))
    base_dir = tmp_path / 'base'
    weights.save_hf_checkpoint(cfg, params, str(base_dir))

    run_dir = tmp_path / 'lora-run'
    sft.main(['--model', 'debug', '--base-checkpoint', str(base_dir),
              '--lora-rank', '2', '--steps', '2', '--batch', '2',
              '--seq', '16', '--checkpoint-dir', str(run_dir),
              '--checkpoint-every', '1', '--log-every', '1'])

    out_dir = tmp_path / 'merged'
    export_lora.main(['--base', str(base_dir), '--adapter', str(run_dir),
                      '--out', str(out_dir), '--lora-rank', '2'])

    def gen(ckpt):
        eng = server_lib.build_engine(checkpoint=str(ckpt), num_slots=1,
                                      max_seq_len=64, dtype='float32')
        eng.start()
        try:
            return eng.generate([5, 9, 2, 31],
                                engine_lib.SamplingParams(
                                    max_new_tokens=8))
        finally:
            eng.stop()

    merged_out = gen(out_dir)
    assert len(merged_out) == 8
    # The merge is not an identity: the merged kernels differ from the
    # base (B inits at zero, but 2 train steps moved it). Token-level
    # output can coincide on a tiny model, so compare weights directly.
    base_params = weights.load_llama_params(cfg, str(base_dir))
    merged_params = weights.load_llama_params(
        weights.load_config(str(out_dir), max_seq_len=64),
        str(out_dir))
    wq_base = np.asarray(
        base_params['params']['layers']['attn']['wq']['kernel'])
    wq_merged = np.asarray(
        merged_params['params']['layers']['attn']['wq']['kernel'])
    assert not np.allclose(wq_base, wq_merged)
