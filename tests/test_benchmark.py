"""Benchmark harness tests: callback summary format offline; full
launch → collect → interpolate → terminate loop on the local provider.

Reference: sky/benchmark/ + sky_callback (SURVEY.md §2.9).
"""
import json
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import callbacks
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import state
from skypilot_tpu.benchmark import benchmark_state
from skypilot_tpu.benchmark import benchmark_utils

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
# Most tests here are fast pure-python; only the engine-building / subprocess
# ones are marked heavy individually.


def test_callback_summary(tmp_path):
    cb = callbacks.SkytCallback(total_steps=10,
                                benchmark_dir=str(tmp_path),
                                warmup_steps=1)
    for _ in range(5):
        time.sleep(0.01)
        cb.on_step_end()
    cb.close()
    with open(tmp_path / 'summary.json', encoding='utf-8') as f:
        s = json.load(f)
    assert s['num_steps'] == 5
    assert s['total_steps'] == 10
    assert s['seconds_per_step'] > 0
    assert s['first_step_time'] <= s['last_step_time']


def test_step_timer_context(tmp_path):
    with callbacks.step_timer(total_steps=3,
                              benchmark_dir=str(tmp_path)) as cb:
        cb.on_step_end()
    with open(tmp_path / 'summary.json', encoding='utf-8') as f:
        assert json.load(f)['num_steps'] == 1


def test_wrap_steps_adapter(tmp_path):
    """Generic iterator adapter — the JAX-native integration."""
    seen = list(callbacks.wrap_steps(range(4), total_steps=4,
                                     benchmark_dir=str(tmp_path)))
    assert seen == [0, 1, 2, 3]
    with open(tmp_path / 'summary.json', encoding='utf-8') as f:
        s = json.load(f)
    assert s['num_steps'] == 4 and s['total_steps'] == 4


def test_wrap_steps_break_counts_final_step(tmp_path):
    """break exits via GeneratorExit at the yield; the in-progress
    step's work completed, so it must still be counted."""
    for i in callbacks.wrap_steps(range(10), total_steps=10,
                                  benchmark_dir=str(tmp_path)):
        if i == 2:
            break
    with open(tmp_path / 'summary.json', encoding='utf-8') as f:
        assert json.load(f)['num_steps'] == 3


def test_hf_trainer_callback_adapter(tmp_path):
    """transformers.TrainerCallback adapter (reference:
    sky_callback/integrations); driven with the real TrainerCallback
    protocol objects but no actual training run."""
    import types

    cb = callbacks.hf_trainer_callback(benchmark_dir=str(tmp_path))
    from transformers import TrainerCallback
    assert isinstance(cb, TrainerCallback)
    state = types.SimpleNamespace(max_steps=7)
    cb.on_train_begin(None, state, None)
    for _ in range(3):
        cb.on_step_end(None, state, None)
    cb.on_train_end(None, state, None)
    with open(tmp_path / 'summary.json', encoding='utf-8') as f:
        s = json.load(f)
    assert s['num_steps'] == 3 and s['total_steps'] == 7


def test_lightning_callback_adapter(tmp_path):
    """PyTorch Lightning adapter (reference:
    sky_callback/integrations/pytorch_lightning.py analog); Lightning is
    not in the image, so the stub-Trainer path drives the same hooks the
    real Trainer would."""
    import types

    cb = callbacks.lightning_callback(benchmark_dir=str(tmp_path))
    trainer = types.SimpleNamespace(global_rank=0,
                                    estimated_stepping_batches=9)
    cb.on_train_start(trainer, None)
    for i in range(4):
        cb.on_train_batch_end(trainer, None, None, None, i)
    cb.on_train_end(trainer, None)
    with open(tmp_path / 'summary.json', encoding='utf-8') as f:
        s = json.load(f)
    assert s['num_steps'] == 4 and s['total_steps'] == 9


def test_lightning_callback_nonzero_rank_records_nothing(tmp_path):
    """Only global rank 0 writes a summary (one per run, matching the
    reference); other ranks' hooks are no-ops."""
    import types

    cb = callbacks.lightning_callback(benchmark_dir=str(tmp_path))
    trainer = types.SimpleNamespace(global_rank=1,
                                    estimated_stepping_batches=9)
    cb.on_train_start(trainer, None)
    cb.on_train_batch_end(trainer, None, None, None, 0)
    cb.on_train_end(trainer, None)
    assert not (tmp_path / 'summary.json').exists()


@pytest.mark.heavy
def test_serve_bench_doc_workload_spec_decode(tmp_path):
    """Doc-grounded workload + spec decode: the bench must report
    speculation accounting (verify steps ran; acceptance measured).
    Random-token prompts would measure ~0 acceptance by construction —
    the doc workload exists so the spec number means something."""
    from skypilot_tpu.benchmark import serve_bench

    cfg = serve_bench.ServeBenchConfig(
        model='debug', prompt_len=24, max_new_tokens=8, num_requests=3,
        num_slots=2, max_seq_len=64, decode_chunk=4,
        spec_decode=2, workload='doc')
    r = serve_bench.run_serve_bench(cfg)
    assert r['spec_verify_steps'] > 0
    assert r['spec_accept_per_step'] >= 0.0
    assert r['decode_tok_per_sec_steady'] >= 0.0


def test_serve_bench_doc_prompts_repeat_ngrams():
    """The doc generator's whole point: internal n-gram repetition —
    exercised on the REAL generator the bench runs."""
    from skypilot_tpu.benchmark import serve_bench
    import numpy as np

    rng = np.random.default_rng(0)
    for _ in range(8):
        toks = serve_bench.doc_prompt(rng, vocab=100, prompt_len=48)
        assert len(toks) == 48
        # 48 tokens = 6 tiles from 4 phrases: pigeonhole guarantees a
        # repeated phrase, hence a repeated 4-gram.
        grams = [tuple(toks[i:i + 4]) for i in range(len(toks) - 3)]
        assert len(set(grams)) < len(grams)


def test_serve_bench_unknown_workload_raises():
    from skypilot_tpu.benchmark import serve_bench
    import pytest as _pytest

    cfg = serve_bench.ServeBenchConfig(model='debug', workload='docs')
    with _pytest.raises(ValueError, match='workload'):
        serve_bench.run_serve_bench(cfg)


def test_interpolation():
    summary = {'boot_time': 100.0, 'num_steps': 10, 'total_steps': 110,
               'first_step_time': 101.0, 'last_step_time': 120.0,
               'seconds_per_step': 2.0}
    r = benchmark_utils._interpolate(summary, hourly_cost=3.6)  # pylint: disable=protected-access
    assert r['elapsed_s'] == 20.0
    assert r['cost_so_far'] == pytest.approx(0.02)
    assert r['eta_s'] == 200.0
    assert r['est_total_s'] == 220.0
    assert r['cost_per_step'] == pytest.approx(0.002)


@pytest.fixture()
def bench_env(tmp_path, tmp_state_dir, monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))
    state.reset_db_for_testing()
    benchmark_state.reset_db_for_testing()
    yield
    from skypilot_tpu import core
    for rec in state.get_clusters():
        try:
            core.down(rec['name'], purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
    state.reset_db_for_testing()
    benchmark_state.reset_db_for_testing()


_BENCH_RUN = (
    "python -c \""
    "import time\n"
    "from skypilot_tpu import callbacks\n"
    "cb = callbacks.SkytCallback(total_steps=4, warmup_steps=0)\n"
    "for _ in range(4):\n"
    "    time.sleep(0.05); cb.on_step_end()\n"
    "cb.close()\"")


@pytest.mark.integration
@pytest.mark.heavy
def test_benchmark_end_to_end(bench_env):
    t = sky.Task(name='bt', run=_BENCH_RUN)
    t.set_resources(resources_lib.Resources(cloud='local'))
    candidates = benchmark_utils.generate_benchmark_candidates(t)
    assert len(candidates) == 1
    benchmark_state.add_benchmark('b1', 'inline')
    clusters = benchmark_utils.launch_benchmark_clusters('b1', t,
                                                         candidates)
    assert clusters == ['skyt-bench-b1-0']
    assert benchmark_utils.wait_for_results('b1', timeout=60,
                                            min_steps=4)
    rows = benchmark_utils.report('b1')
    assert rows[0]['num_steps'] == 4
    assert rows[0]['seconds_per_step'] > 0
    benchmark_utils.terminate_benchmark_clusters('b1')
    assert state.get_clusters() == []
    assert benchmark_state.get_results('b1')[0]['status'] is \
        benchmark_state.BenchmarkStatus.TERMINATED
