"""Training-plane observability: heartbeats, gang watchdog, postmortem
bundles, HUNG escalation, and the prefix-cache sync satellite
(docs/observability.md "Training plane").

Everything here runs under injected clocks — the hang/straggler/desync
truth table is deterministic, no sleeps except the (real-thread)
sentinel test.
"""
import json
import os
import time

import pytest

from skypilot_tpu.train import heartbeat as heartbeat_lib
from skypilot_tpu.train import postmortem as postmortem_lib
from skypilot_tpu.train import watchdog as watchdog_lib
from skypilot_tpu.utils import metrics as metrics_lib


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture()
def wd_env(monkeypatch):
    """Deterministic watchdog thresholds for the truth table."""
    monkeypatch.setenv('SKYT_WATCHDOG_MIN_S', '1')
    monkeypatch.setenv('SKYT_WATCHDOG_FACTOR', '5')
    monkeypatch.setenv('SKYT_WATCHDOG_STRAGGLER_K', '3')
    monkeypatch.setenv('SKYT_WATCHDOG_PIPELINE_DEPTH', '2')
    monkeypatch.setenv('SKYT_WATCHDOG_CONFIRM', '2')


# ================================================================ heartbeat
def test_heartbeat_record_and_ewma_deterministic(tmp_path):
    clock = FakeClock()
    path = str(tmp_path / 'hb.json')
    w = heartbeat_lib.HeartbeatWriter(path, 3, clock=clock,
                                      interval_s=0,
                                      registry=metrics_lib.MetricsRegistry())
    w.mark_phase('compile')
    assert heartbeat_lib.read(path)['phase'] == 'compile'
    for i in range(6):
        clock.advance(0.5)
        w.on_step(i, tokens_per_sec=42.0)
    rec = heartbeat_lib.read(path)
    assert rec['rank'] == 3 and rec['step'] == 5
    assert rec['phase'] == 'step' and rec['ts'] == clock.t
    # Constant 0.5s steps -> EWMA converges to exactly 0.5.
    assert abs(rec['ewma_step_s'] - 0.5) < 1e-9
    assert rec['tokens_per_sec'] == 42.0
    # No torn/tmp files left behind by the atomic write.
    assert [p.name for p in tmp_path.iterdir()] == ['hb.json']


def test_heartbeat_write_throttle_and_metrics(tmp_path):
    clock = FakeClock()
    reg = metrics_lib.MetricsRegistry()
    path = str(tmp_path / 'hb.json')
    w = heartbeat_lib.HeartbeatWriter(path, 0, clock=clock,
                                      interval_s=10, registry=reg)
    clock.advance(1)
    w.on_step(1)
    clock.advance(1)
    w.on_step(2)          # within the interval: file stays at step 1
    assert heartbeat_lib.read(path)['step'] == 1
    clock.advance(10)
    w.on_step(3)
    assert heartbeat_lib.read(path)['step'] == 3
    # Metrics update EVERY step regardless of the file throttle.
    assert reg.get('skyt_train_heartbeat_step').value('0') == 3.0
    assert reg.get('skyt_train_step_seconds').value() > 0
    # In-memory snapshot is always current.
    assert w.snapshot()['step'] == 3


def test_heartbeat_read_tolerates_garbage(tmp_path):
    p = tmp_path / 'hb.json'
    assert heartbeat_lib.read(str(p)) is None
    p.write_text('{torn')
    assert heartbeat_lib.read(str(p)) is None
    p.write_text('[1, 2]')
    assert heartbeat_lib.read(str(p)) is None


def test_writer_from_env_gating(monkeypatch, tmp_path):
    monkeypatch.setenv('SKYT_WATCHDOG', '0')
    assert heartbeat_lib.writer_from_env() is None
    monkeypatch.setenv('SKYT_WATCHDOG', '1')
    monkeypatch.setenv('SKYT_NODE_RANK', '2')
    monkeypatch.setenv('SKYT_HEARTBEAT_FILE', str(tmp_path / 'h.json'))
    w = heartbeat_lib.writer_from_env()
    assert w is not None and w.rank == 2
    assert w.path == str(tmp_path / 'h.json')


# ================================================== watchdog truth table
def _rec(rank, ts, step=10, ewma=0.1, phase='step'):
    return {'rank': rank, 'step': step, 'phase': phase, 'ts': ts,
            'ewma_step_s': ewma}


def _gang(clock, n=2, registry=None):
    return watchdog_lib.GangWatchdog(
        n, clock=clock,
        registry=registry or metrics_lib.MetricsRegistry())


def test_verdict_init_before_any_stepping(wd_env):
    clock = FakeClock()
    wd = _gang(clock)
    assert wd.evaluate().state == 'init'
    wd.observe(0, _rec(0, clock.t, phase='compile'))
    wd.observe(1, _rec(1, clock.t, phase='init'))
    # Compiling for a long time is NOT a hang: no stall budget applies
    # until a rank reaches phase 'step'.
    clock.advance(3600)
    assert wd.evaluate().state == 'init'


def test_verdict_ok_and_hang_budget(wd_env):
    clock = FakeClock()
    wd = _gang(clock)
    wd.observe(0, _rec(0, clock.t))
    wd.observe(1, _rec(1, clock.t))
    assert wd.evaluate().state == 'ok'
    # Silence below the floor (min_s=1 > 5*0.1 ewma budget) stays ok.
    clock.advance(0.9)
    assert wd.evaluate().state == 'ok'
    # Past max(factor*ewma, min_s): hang, naming the stalled rank.
    clock.advance(0.2)
    v = wd.evaluate()
    assert v.state == 'hang'
    assert set(v.detail['stalled_ranks']) == {0, 1}


def test_hang_floor_scales_with_ewma(wd_env):
    clock = FakeClock()
    wd = _gang(clock)
    # Slow steps (1s EWMA): budget = 5*1 = 5s > the 1s floor.
    wd.observe(0, _rec(0, clock.t, ewma=1.0))
    wd.observe(1, _rec(1, clock.t, ewma=1.0))
    clock.advance(4.5)
    assert wd.evaluate().state == 'ok'
    clock.advance(1.0)
    assert wd.evaluate().state == 'hang'


def test_hang_confirmation_streak(wd_env):
    clock = FakeClock()
    wd = _gang(clock)
    wd.observe(0, _rec(0, clock.t))
    wd.observe(1, _rec(1, clock.t))
    clock.advance(5)
    v1 = wd.evaluate()
    assert v1.state == 'hang' and not v1.confirmed
    v2 = wd.evaluate()
    assert v2.confirmed
    # A fresh heartbeat resets the streak.
    wd.observe(0, _rec(0, clock.t))
    wd.observe(1, _rec(1, clock.t))
    assert wd.evaluate().state == 'ok'
    clock.advance(5)
    assert not wd.evaluate().confirmed


def test_verdict_straggler(wd_env):
    clock = FakeClock()
    wd = _gang(clock, n=3)
    wd.observe(0, _rec(0, clock.t, ewma=0.1))
    wd.observe(1, _rec(1, clock.t, ewma=0.12))
    wd.observe(2, _rec(2, clock.t, ewma=0.9))   # > 3x median (0.12)
    v = wd.evaluate()
    assert v.state == 'straggler'
    assert list(v.detail['straggler_ranks']) == [2]
    # K is env-tunable: a huge K clears the verdict.
    os.environ['SKYT_WATCHDOG_STRAGGLER_K'] = '100'
    try:
        assert wd.evaluate().state == 'ok'
    finally:
        os.environ['SKYT_WATCHDOG_STRAGGLER_K'] = '3'


def test_verdict_desync_and_hang_precedence(wd_env):
    clock = FakeClock()
    wd = _gang(clock)
    wd.observe(0, _rec(0, clock.t, step=10))
    wd.observe(1, _rec(1, clock.t, step=20))    # skew 10 > depth 2
    assert wd.evaluate().state == 'desync'
    # Hang wins over desync (a hung rank drags survivors apart —
    # report the cause, not the symptom).
    clock.advance(5)
    assert wd.evaluate().state == 'hang'


def test_watchdog_metrics_and_spans(wd_env, monkeypatch):
    from skypilot_tpu.utils import tracing
    monkeypatch.setenv('SKYT_TRACE', '1')
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '0')
    clock = FakeClock()
    reg = metrics_lib.MetricsRegistry()
    tracer = tracing.Tracer(service='wd-test')
    wd = watchdog_lib.GangWatchdog(2, clock=clock, registry=reg,
                                   tracer=tracer, job='7')
    wd.observe(0, _rec(0, clock.t))
    wd.observe(1, _rec(1, clock.t))
    wd.evaluate()
    gauge = reg.get('skyt_train_gang_state')
    assert gauge.value('7', 'ok') == 1.0
    assert gauge.value('7', 'hang') == 0.0
    clock.advance(5)
    wd.evaluate()
    assert gauge.value('7', 'hang') == 1.0
    assert gauge.value('7', 'ok') == 0.0
    assert reg.get(
        'skyt_train_watchdog_verdicts_total').value('7', 'hang') == 1.0
    # Concurrent jobs don't clobber each other's series (the head runs
    # one evaluator per job on a shared registry)...
    other = watchdog_lib.GangWatchdog(2, clock=clock, registry=reg,
                                      job='8')
    other.observe(0, _rec(0, clock.t))
    other.observe(1, _rec(1, clock.t))
    other.evaluate()
    assert gauge.value('8', 'ok') == 1.0
    assert gauge.value('7', 'hang') == 1.0   # job 7's verdict intact
    # ...and a retired job's series are dropped, not leaked.
    wd.retire()
    assert ('7', 'hang') not in gauge.label_keys()
    assert ('8', 'ok') in gauge.label_keys()
    # Forced-sampled transition span survives head-sampling at 0.
    names = [s['name'] for r in tracer.store.records()
             for s in r['spans']]
    assert 'watchdog.hang' in names


def test_classify_stall_shared_helper(wd_env):
    now = 100.0
    assert not watchdog_lib.classify_stall(None, now)['stalled']
    assert not watchdog_lib.classify_stall(
        _rec(0, now - 999, phase='compile'), now)['stalled']
    c = watchdog_lib.classify_stall(_rec(0, now - 2.0), now)
    assert c['stalled'] and c['stalled_for_s'] == 2.0
    assert c['budget_s'] == 1.0


# =============================================================== sentinel
def test_rank_sentinel_fires_once_and_dumps(tmp_path, monkeypatch):
    """Real-thread sentinel: stall past the budget -> exactly one
    on_stall callback carrying the stall classification."""
    monkeypatch.setenv('SKYT_WATCHDOG_MIN_S', '0.3')
    monkeypatch.setenv('SKYT_WATCHDOG_FACTOR', '2')
    w = heartbeat_lib.HeartbeatWriter(None, 0, interval_s=0)
    fired = []
    s = watchdog_lib.RankSentinel(w, fired.append, poll_s=0.05).start()
    try:
        w.on_step(1)
        time.sleep(0.15)
        assert not fired          # still within budget
        deadline = time.time() + 10
        while not s.fired.is_set() and time.time() < deadline:
            time.sleep(0.05)
        assert len(fired) == 1
        assert fired[0]['stall']['stalled']
        time.sleep(0.2)
        assert len(fired) == 1    # one bundle per stall episode
    finally:
        s.stop()


# ============================================================= postmortem
def test_postmortem_bundle_contents_and_index(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYT_POSTMORTEM_DIR', str(tmp_path))
    monkeypatch.setenv('SKYT_JOB_ID', '7')
    path = postmortem_lib.dump_bundle(
        'hang', rank=1, heartbeat={'step': 4, 'phase': 'step'},
        train_state={'step': 4, 'prefetch_resident': 2})
    assert path and os.path.isdir(path)
    # py-stacks include THIS thread (faulthandler all_threads).
    stacks = open(os.path.join(path, 'stacks.txt')).read()
    assert 'test_postmortem_bundle_contents_and_index' in stacks
    spans = json.load(open(os.path.join(path, 'spans.json')))
    assert 'traces' in spans and 'summaries' in spans
    state = json.load(open(os.path.join(path, 'state.json')))
    assert state['reason'] == 'hang' and state['rank'] == 1
    assert state['job_id'] == '7'
    assert state['heartbeat']['step'] == 4
    assert state['train']['prefetch_resident'] == 2
    assert state['env']['SKYT_JOB_ID'] == '7'
    # Atomic: no .tmp staging dirs remain.
    assert not [n for n in os.listdir(tmp_path) if n.startswith('.tmp')]
    idx = postmortem_lib.list_bundles()
    assert len(idx) == 1
    assert idx[0]['reason'] == 'hang' and idx[0]['rank'] == 1
    assert sorted(idx[0]['files']) == ['spans.json', 'stacks.txt',
                                       'state.json']
    # Foreign files and torn bundles don't break the index.
    (tmp_path / 'unrelated.txt').write_text('x')
    broken = tmp_path / 'postmortem-19700101-000000-rank9-1'
    broken.mkdir()
    idx = postmortem_lib.list_bundles()
    assert len(idx) == 2
    assert any('error' in e for e in idx)


def test_postmortem_stacks_survive_thread_truncation(tmp_path,
                                                     monkeypatch):
    """faulthandler's all-threads dump caps at 100 threads (newest
    first), so in a thread-heavy process the requesting thread — the
    one that diagnosed the hang — is exactly the one truncated away.
    dump_bundle writes it separately so it always survives."""
    import threading
    monkeypatch.setenv('SKYT_POSTMORTEM_DIR', str(tmp_path))
    release = threading.Event()
    extra = [threading.Thread(target=release.wait, daemon=True)
             for _ in range(110)]
    try:
        for t in extra:
            t.start()
        path = postmortem_lib.dump_bundle('hang', rank=0)
        stacks = open(os.path.join(path, 'stacks.txt')).read()
        assert '...' in stacks          # the cap really was hit
        assert 'test_postmortem_stacks_survive_thread_truncation' \
            in stacks
    finally:
        release.set()
        for t in extra:
            t.join(timeout=5)


def test_postmortem_dump_never_raises(tmp_path, monkeypatch):
    # Unusable root (a FILE occupies the path — mkdir can never
    # succeed, even for root): dump returns None instead of raising
    # into a dying process.
    (tmp_path / 'f').write_text('not a dir')
    monkeypatch.setenv('SKYT_POSTMORTEM_DIR',
                       str(tmp_path / 'f' / 'x'))
    assert postmortem_lib.dump_bundle('crash') is None


# ====================================================== head escalation
def test_head_state_hang_escalates_to_hung(tmp_path, monkeypatch,
                                           wd_env):
    """Relayed heartbeats -> confirmed hang -> terminal HUNG + kill
    directives for every rank; a later cooperative rc=75 from a
    SIGTERM'd survivor must not relabel the hang."""
    monkeypatch.setenv('SKYT_AGENT_HOME', str(tmp_path))
    from skypilot_tpu.runtime import job_lib
    from skypilot_tpu.runtime import server as rt_server
    job_lib.reset_db_for_testing()
    clock = FakeClock()
    head = rt_server.HeadState(rt_server.ClusterConfig(
        {'cluster_name': 'c', 'num_nodes': 2,
         'ips': ['127.0.0.1', '127.0.0.2']}), clock=clock)
    jid = head.submit({'name': 'j', 'run': 'x', 'num_nodes': 2})
    head.schedule_step()
    head.report(jid, 0, 'run_started')
    head.report(jid, 1, 'run_started')

    head.record_heartbeat(jid, 0, _rec(0, clock.t))
    head.record_heartbeat(jid, 1, _rec(1, clock.t),
                          postmortems=['/logs/postmortem-a-rank1-9'])
    head.watchdog_tick()
    assert job_lib.get_job(jid)['status'] is job_lib.JobStatus.RUNNING

    clock.advance(10)                       # rank 1 goes silent
    head.record_heartbeat(jid, 0, _rec(0, clock.t))
    head.watchdog_tick()                    # hang streak 1
    assert job_lib.get_job(jid)['status'] is job_lib.JobStatus.RUNNING
    head.watchdog_tick()                    # confirmed
    assert job_lib.get_job(jid)['status'] is job_lib.JobStatus.HUNG
    for rank in (0, 1):
        assert any(d['action'] == 'kill'
                   for d in head.work_for_rank(rank))
    obs = head.job_observability(jid)
    assert obs['watchdog']['state'] == 'hang'
    assert obs['watchdog']['confirmed'] is True
    assert obs['postmortems']['1'] == ['/logs/postmortem-a-rank1-9']
    assert obs['heartbeats']['0']['step'] == 10
    # Survivor's SIGTERM-path 75 must not downgrade HUNG -> PREEMPTED.
    head.report(jid, 0, 'done', job_lib.EXIT_CODE_PREEMPTED)
    assert job_lib.get_job(jid)['status'] is job_lib.JobStatus.HUNG
    # Terminal job: the next tick retires the evaluator but keeps the
    # verdict for the wire.
    head.watchdog_tick()
    assert jid not in head.watchdogs
    assert head.job_observability(jid)['watchdog']['state'] == 'hang'


def test_hung_is_terminal_and_recovered_by_controller():
    from skypilot_tpu.runtime import job_lib
    assert job_lib.JobStatus.HUNG.is_terminal()
    # The managed-jobs watch loop recovers HUNG via the same branch as
    # PREEMPTED (jobs/controller.py) — pin the literal the probe
    # compares against so a status rename can't silently break it.
    import inspect

    from skypilot_tpu.jobs import controller as jobs_controller
    src = inspect.getsource(jobs_controller.JobsController._run_one_task)
    assert "'HUNG'" in src and "'PREEMPTED'" in src


# ============================================== /fleet/postmortems route
def test_fleet_postmortems_route(tmp_path, monkeypatch):
    import asyncio

    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from skypilot_tpu.serve import fleet as fleet_lib
    monkeypatch.setenv('SKYT_POSTMORTEM_DIR', str(tmp_path))
    postmortem_lib.dump_bundle('hang', rank=0)
    fl = fleet_lib.FleetTelemetry(
        'svc', metrics_registry=metrics_lib.MetricsRegistry())

    async def run():
        app = web.Application()
        fleet_lib.add_fleet_routes(app, fl, lambda rid: None)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.get('/fleet/postmortems')
            assert resp.status == 200
            body = await resp.json()
            assert body['root'] == str(tmp_path)
            assert len(body['bundles']) == 1
            assert body['bundles'][0]['reason'] == 'hang'
            resp = await client.get('/fleet/postmortems',
                                    params={'limit': '0'})
            assert resp.status == 400
        finally:
            await client.close()

    asyncio.run(run())


# ================================== prefix-cache sync satellite (LB side)
def test_lb_prefix_cache_gauge_tracks_sync(monkeypatch):
    from skypilot_tpu.serve import load_balancer as lb_lib
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '3600')
    reg = metrics_lib.MetricsRegistry()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', 0,
                                     metrics_registry=reg)
    state = lb_lib.LBState(
        ready_replicas=['http://a', 'http://b'],
        replica_prefix_cache={
            'http://a': {'occupancy': 0.75, 'cached_pages': 12},
            'http://b': {'hit_pages': 3}},        # no occupancy: skip
        synced_at=1.0, version=1)
    lb.apply_state(state)
    gauge = reg.get('skyt_lb_replica_prefix_cache')
    assert gauge.value(lb.lb_id, 'http://a') == 0.75
    assert (lb.lb_id, 'http://b') not in gauge.label_keys()
    # Replica leaves the sync: its series is pruned.
    lb.apply_state(lb_lib.LBState(ready_replicas=['http://b'],
                                  synced_at=2.0, version=2))
    assert (lb.lb_id, 'http://a') not in gauge.label_keys()
    # Snapshot roundtrip carries the block (standby mirrors see it).
    restored = lb_lib.LBState.from_json(state.to_json())
    assert restored.replica_prefix_cache['http://a']['occupancy'] == \
        0.75


def test_replica_manager_scrapes_prefix_cache(monkeypatch):
    """ready_prefix_cache() narrows to READY replicas whose /stats
    carried a prefix_cache block (the controller sync source)."""
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve import serve_state
    assert 'prefix_cache' in replica_managers.ReplicaManager._STATS_KEYS

    rm = object.__new__(replica_managers.ReplicaManager)
    rm._lock = __import__('threading').Lock()

    class R:
        def __init__(self, status, endpoint, stats):
            self.status = status
            self.endpoint = endpoint
            self.stats = stats

    ready = serve_state.ReplicaStatus.READY
    rm.replicas = {
        1: R(ready, 'http://a', {'prefix_cache': {'occupancy': 0.5}}),
        2: R(ready, 'http://b', {'qos': {}}),            # no block
        3: R(serve_state.ReplicaStatus.NOT_READY, 'http://c',
             {'prefix_cache': {'occupancy': 0.9}}),      # not ready
    }
    out = replica_managers.ReplicaManager.ready_prefix_cache(rm)
    assert out == {'http://a': {'occupancy': 0.5}}


def test_engine_prefix_cache_occupancy_in_stats():
    """The paged pool reports cached pages; the engine folds occupancy
    into the /stats prefix_cache block the controller scrapes."""
    import jax.numpy as jnp

    from skypilot_tpu.infer import paged_cache
    cfg = paged_cache.PagedConfig(page_size=4, n_pages=9,
                                  max_pages_per_slot=4)
    pool = paged_cache.PagePool(cfg, n_layers=1, kv_heads=1, head_dim=4,
                                num_slots=2, dtype=jnp.float32)
    assert pool.prefix_cached_pages() == 0
    row = pool.try_reserve_prefix(0, 8, ())
    assert row is not None
    pool.publish(0, [b'h0', b'h1'])
    assert pool.prefix_cached_pages() == 2
