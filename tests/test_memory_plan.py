"""Serving memory plans: the 70B-on-v5e recipes are pinned here.

These tests are the feasibility proof for examples/llama_70b_serve.yaml
(VERDICT r4 item 4): the plan reproduces the engine's real placement
arithmetic, so a passing assertion means the engine's arrays fit.
"""
import dataclasses

import pytest

from skypilot_tpu.infer import memory_plan
from skypilot_tpu.models import llama


def _cfg70b():
    return dataclasses.replace(llama.CONFIGS['llama3-70b'],
                               dtype='bfloat16', param_dtype='bfloat16')


def test_70b_int8_tp8_fits_v5e8():
    """The recipe: 70B int8 over a v5e-8 (2 hosts x 4 chips, tp=8).
    KV shards 8-ways (8 kv heads), params ~8.5 GiB/chip."""
    plan = memory_plan.plan_serving(_cfg70b(), tp=8, num_slots=8,
                                    max_seq_len=4096, quantize='int8')
    assert plan.kv_sharded
    assert plan.fits, plan.summary()
    assert plan.headroom_gib > 2.0, plan.summary()


def test_70b_int8_tp16_replicated_kv_does_not_fit():
    """tp=16 does NOT divide the 8 kv heads -> the engine replicates
    the pool on every chip and the plan correctly rejects it: more
    chips is not automatically more capacity. This is why the recipe
    says tp=8."""
    plan = memory_plan.plan_serving(_cfg70b(), tp=16, num_slots=8,
                                    max_seq_len=4096, quantize='int8')
    assert not plan.kv_sharded
    assert not plan.fits, plan.summary()


def test_70b_bf16_needs_more_than_v5e8():
    """bf16 70B (~141 GiB of weights) cannot fit 8 x 16 GiB — int8 is
    load-bearing for the recipe, not an optimization."""
    plan = memory_plan.plan_serving(_cfg70b(), tp=8, num_slots=8,
                                    max_seq_len=4096, quantize='none')
    assert not plan.fits, plan.summary()


def test_8b_int8_fits_one_chip():
    """Cross-check against the measured config: 8B int8 on a single
    v5e chip (examples/llama_8b_int8_serve.yaml runs this today)."""
    cfg = dataclasses.replace(llama.CONFIGS['llama3-8b'],
                              dtype='bfloat16', param_dtype='bfloat16')
    plan = memory_plan.plan_serving(cfg, tp=1, num_slots=8,
                                    max_seq_len=2048, quantize='int8')
    assert plan.fits, plan.summary()


def test_pool_tokens_shrinks_kv():
    cfg = _cfg70b()
    full = memory_plan.plan_serving(cfg, tp=8, quantize='int8')
    half = memory_plan.plan_serving(cfg, tp=8, quantize='int8',
                                    pool_tokens=8 * 4096 // 2)
    assert half.kv_pool_bytes < full.kv_pool_bytes


def test_unknown_quant_rejected():
    with pytest.raises(ValueError, match='quantize'):
        memory_plan.plan_serving(_cfg70b(), tp=8, quantize='int4')


def test_stream_load_budget_reads_checkpoint_bytes():
    """int8 serving still reads the full bf16 checkpoint (quantize
    happens on host mid-stream): ~141 GiB -> ~141 s/host at 1 GB/s."""
    s = memory_plan.stream_load_budget_s(_cfg70b(), read_gbps=1.0)
    assert 130 < s < 160, s
