"""On-TPU lowering smoke: the kernels and hot paths must COMPILE AND RUN
on the real chip, not just in interpret mode (VERDICT r2 next-round #2).

Covers the exact regression class that shipped broken in round 2: a
Pallas BlockSpec that passes interpret mode but is rejected by Mosaic.

Run via format.sh (auto-skips off-TPU). Shapes are the real ones:
seq 2048 bf16 GQA for the kernel, a flash-routed train step, and one
engine prefill+decode.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu


def _rand(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.bfloat16)


class TestFlashKernelLowers:
    """Compile + run fwd/bwd at seq 2048 bf16 GQA and check vs reference."""

    def test_fwd_bwd_seq2048_gqa(self):
        from skypilot_tpu.ops.attention import mha_reference
        from skypilot_tpu.ops.flash_attention import flash_attention

        b, s, hq, hkv, d = 2, 2048, 8, 4, 128
        q = _rand(0, (b, s, hq, d))
        k = _rand(1, (b, s, hkv, d))
        v = _rand(2, (b, s, hkv, d))

        def loss(fn):
            return lambda q, k, v: fn(q, k, v, causal=True).astype(
                jnp.float32).mean()

        out = jax.jit(flash_attention, static_argnames=('causal',))(
            q, k, v, causal=True)
        ref = jax.jit(mha_reference, static_argnames=('causal',))(
            q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)

        grads = jax.jit(jax.grad(loss(flash_attention), argnums=(0, 1, 2)))(
            q, k, v)
        grefs = jax.jit(jax.grad(loss(mha_reference), argnums=(0, 1, 2)))(
            q, k, v)
        for g, gr in zip(grads, grefs):
            # bf16 inputs + different accumulation order: loose tolerance,
            # this is a lowering gate, not the numerics test (tests/ has
            # the tight interpret-mode comparison).
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(gr, np.float32),
                atol=5e-2, rtol=5e-2)

    def test_windowed_fwd_bwd(self):
        """Sliding-window flash (Mistral/Phi-3 prefill): Mosaic
        lowering + parity vs the masked reference at seq 2048, window
        512 — validates flipping SKYT_WINDOW_FLASH to default-on."""
        from skypilot_tpu.ops.attention import mha_reference
        from skypilot_tpu.ops.flash_attention import flash_attention

        b, s, hq, hkv, d, w = 2, 2048, 8, 4, 128, 512
        q = _rand(0, (b, s, hq, d))
        k = _rand(1, (b, s, hkv, d))
        v = _rand(2, (b, s, hkv, d))

        out = jax.jit(flash_attention,
                      static_argnames=('causal', 'window'))(
            q, k, v, causal=True, window=w)
        ref = jax.jit(mha_reference,
                      static_argnames=('causal', 'window'))(
            q, k, v, causal=True, window=w)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)

        def loss(fn):
            return lambda q, k, v: fn(
                q, k, v, causal=True, window=w).astype(
                jnp.float32).mean()
        grads = jax.jit(jax.grad(loss(flash_attention),
                                 argnums=(0, 1, 2)))(q, k, v)
        grefs = jax.jit(jax.grad(loss(mha_reference),
                                 argnums=(0, 1, 2)))(q, k, v)
        for g, gr in zip(grads, grefs):
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(gr, np.float32),
                atol=5e-2, rtol=5e-2)

    def test_fwd_with_segment_ids(self):
        from skypilot_tpu.ops.flash_attention import flash_attention

        b, s, hq, hkv, d = 1, 1024, 4, 2, 128
        q = _rand(0, (b, s, hq, d))
        k = _rand(1, (b, s, hkv, d))
        v = _rand(2, (b, s, hkv, d))
        seg = jnp.concatenate(
            [jnp.zeros((b, s // 2), jnp.int32),
             jnp.ones((b, s // 2), jnp.int32)], axis=1)
        out = jax.jit(flash_attention,
                      static_argnames=('causal',))(q, k, v, causal=True,
                                                   segment_ids=seg)
        assert out.shape == (b, s, hq, d)
        assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


class TestDispatchShapeGridLowers:
    """The never-crash contract ON-CHIP: every adversarial shape in
    the CPU grid (tests/test_ops_dispatch.py) must lower through the
    real Mosaic pipeline — this is the half the static mirror in
    ops/dispatch.py cannot prove from CPU. Includes the exact
    BENCH_r02 decode shape that zeroed rounds 2-5."""

    @pytest.mark.parametrize('shape', [
        (4, 32, 32, 8, 8, 256),     # BENCH_r02, API layout
        (4, 8, 8, 32, 32, 256),     # BENCH_r02, kernel-layout reading
        (2, 1, 1, 4, 2, 64),        # single-query decode
        (1, 300, 300, 2, 2, 64),    # non-8-divisible seq
        (3, 24, 24, 2, 1, 128),     # odd batch + GQA
    ], ids=lambda s: 'x'.join(map(str, s)))
    def test_grid_shape_lowers(self, shape):
        from skypilot_tpu.ops.attention import mha_reference
        from skypilot_tpu.ops.flash_attention import flash_attention

        b, sq, sk, hq, hkv, d = shape
        q = _rand(0, (b, sq, hq, d))
        k = _rand(1, (b, sk, hkv, d))
        v = _rand(2, (b, sk, hkv, d))
        causal = sq == sk
        out = jax.jit(flash_attention, static_argnames=('causal',))(
            q, k, v, causal=causal)
        ref = jax.jit(mha_reference, static_argnames=('causal',))(
            q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)

    def test_segment_ids_batch_gt_one_lowers(self):
        """Packed sequences with batch > 1: the [B, 1, S] lane-axis
        segment layout must pass Mosaic (the old [B, S] layout was
        illegal for any B > 1 — a latent train crash)."""
        from skypilot_tpu.ops.flash_attention import flash_attention

        b, s, hq, hkv, d = 2, 512, 4, 2, 128
        q = _rand(0, (b, s, hq, d))
        k = _rand(1, (b, s, hkv, d))
        v = _rand(2, (b, s, hkv, d))
        seg = jnp.concatenate(
            [jnp.zeros((b, s // 2), jnp.int32),
             jnp.ones((b, s // 2), jnp.int32)], axis=1)
        out = jax.jit(flash_attention,
                      static_argnames=('causal',))(q, k, v, causal=True,
                                                   segment_ids=seg)
        assert out.shape == (b, s, hq, d)
        assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


class TestTrainStepFlash:
    """One real train step with attn_impl='flash' at seq 512 (the r2 bug
    crashed any seq > 256)."""

    def test_one_train_step(self):
        import flax.linen as nn

        from skypilot_tpu.models import llama
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.parallel import sharding as sharding_lib
        from skypilot_tpu.train import trainer

        cfg = dataclasses.replace(
            llama.CONFIGS['debug'],
            dim=512, n_heads=4, n_kv_heads=2, mlp_dim=1024,
            max_seq_len=512, dtype='bfloat16', param_dtype='bfloat16',
            attn_impl='flash')
        assert cfg.head_dim == 128  # flash-compatible head dim
        model = llama.LlamaModel(cfg)
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec())
        tcfg = trainer.TrainerConfig(warmup_steps=2, total_steps=10)
        tx = trainer.make_optimizer(tcfg)
        batch, seq = 2, 512
        sample = jnp.zeros((batch, seq), jnp.int32)
        state, _ = trainer.create_sharded_state(
            model, tx, mesh, sample, jax.random.PRNGKey(0))
        step = trainer.make_train_step(model, tx, mesh, donate=False)
        toks = jax.random.randint(jax.random.PRNGKey(1),
                                  (batch, seq + 1), 0, cfg.vocab_size,
                                  jnp.int32)
        data = {'tokens': toks[:, :-1], 'targets': toks[:, 1:]}
        with mesh, nn.logical_axis_rules(list(sharding_lib.DEFAULT_RULES)):
            state, metrics = step(state, data)
            loss = float(metrics['loss'])
        assert np.isfinite(loss)


class TestPagedAttentionLowers:
    """The paged decode kernel must compile through Mosaic at serving
    shapes (1B-like: hkv=8, G=4, d=64, P=64) and match the gather
    reference."""

    def test_paged_kernel_matches_gather(self):
        from skypilot_tpu.infer.paged_cache import PagePool
        from skypilot_tpu.ops import attention as attention_ops
        from skypilot_tpu.ops import paged_attention

        rng = np.random.default_rng(0)
        slots, hq, hkv, d, p, mp = 8, 32, 8, 64, 64, 16
        n_pages = slots * mp + 1
        q = jnp.asarray(rng.normal(size=(slots, hq, d)), jnp.bfloat16)
        kp = jnp.asarray(rng.normal(size=(n_pages, hkv, p, d)),
                         jnp.bfloat16)
        vp = jnp.asarray(rng.normal(size=(n_pages, hkv, p, d)),
                         jnp.bfloat16)
        tables = jnp.asarray(
            np.arange(1, 1 + slots * mp).reshape(slots, mp), jnp.int32)
        lengths = jnp.asarray([575, 3, 100, 64, 63, 200, 17, 512],
                              jnp.int32)
        out = paged_attention.paged_decode_attention(q, kp, vp, tables,
                                                     lengths)
        kv = PagePool.gather_view_layer(kp, tables)
        vv = PagePool.gather_view_layer(vp, tables)
        ref = attention_ops.mha_reference(q[:, None], kv, vv,
                                          q_positions=lengths[:, None])
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref[:, 0],
                                                    np.float32),
            atol=3e-2, rtol=3e-2)

    def test_paged_int8_kernel_matches_dequant_gather(self):
        """The int8-KV kernel (k/v int8 pages + scale blocks, dequant
        folded into the matmuls) must lower through Mosaic at the same
        serving shapes and match the dequantizing gather floor."""
        from skypilot_tpu.infer.paged_cache import PagePool
        from skypilot_tpu.ops import attention as attention_ops
        from skypilot_tpu.ops import paged_attention

        rng = np.random.default_rng(1)
        slots, hq, hkv, d, p, mp = 8, 32, 8, 64, 64, 16
        n_pages = slots * mp + 1
        q = jnp.asarray(rng.normal(size=(slots, hq, d)), jnp.bfloat16)
        kp = jnp.asarray(rng.integers(-127, 128,
                                      (n_pages, hkv, p, d)), jnp.int8)
        vp = jnp.asarray(rng.integers(-127, 128,
                                      (n_pages, hkv, p, d)), jnp.int8)
        ks = jnp.asarray(rng.uniform(0.005, 0.02, (n_pages, hkv, p)),
                         jnp.float32)
        vs = jnp.asarray(rng.uniform(0.005, 0.02, (n_pages, hkv, p)),
                         jnp.float32)
        tables = jnp.asarray(
            np.arange(1, 1 + slots * mp).reshape(slots, mp), jnp.int32)
        lengths = jnp.asarray([575, 3, 100, 64, 63, 200, 17, 512],
                              jnp.int32)
        out = paged_attention.paged_decode_attention_q(
            q, kp, vp, ks, vs, tables, lengths)
        kv = PagePool.gather_view_layer_q(kp, ks, tables, jnp.float32)
        vv = PagePool.gather_view_layer_q(vp, vs, tables, jnp.float32)
        ref = attention_ops.mha_reference(
            q.astype(jnp.float32)[:, None], kv, vv,
            q_positions=lengths[:, None])
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref[:, 0],
                                                    np.float32),
            atol=3e-2, rtol=3e-2)


class TestEnginePrefillDecode:
    """One prefill + a few decode steps on the chip, both cache modes
    (paged engages the Pallas paged-attention kernel + layout pin)."""

    @pytest.mark.parametrize('cache_mode', ['dense', 'paged'])
    def test_prefill_decode(self, cache_mode):
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        engine = server_lib.build_engine('debug', num_slots=2,
                                         max_seq_len=128,
                                         cache_mode=cache_mode)
        engine.start()
        try:
            params = engine_lib.SamplingParams(max_new_tokens=4)
            _, q = engine.submit([1, 2, 3, 4, 5, 6, 7, 8], params)
            toks = []
            while True:
                t = q.get(timeout=300)
                if t is None:
                    break
                toks.append(t)
            assert len(toks) == 4
        finally:
            engine.stop()

    def test_spec_mq_kernel_lowers(self, monkeypatch):
        """The multi-query paged-attention kernel must lower through
        Mosaic and match the plain engine (validates flipping
        SKYT_SPEC_PAGED_ATTN to default-pallas)."""
        monkeypatch.setenv('SKYT_SPEC_PAGED_ATTN', 'pallas')
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        prompt = [5, 9, 2] * 8
        outs = {}
        for spec in (4, 0):
            engine = server_lib.build_engine(
                'debug', num_slots=2, max_seq_len=256,
                cache_mode='paged', spec_decode=spec)
            engine.start()
            try:
                outs[spec] = engine.generate(
                    prompt,
                    engine_lib.SamplingParams(max_new_tokens=16))
            finally:
                engine.stop()
        assert outs[4] == outs[0]

    def test_spec_decode_lowers(self):
        """The speculative decode step (multi-token paged append +
        gather-view attention + on-device verify) must lower and match
        the plain greedy engine on the chip."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        prompt = [5, 9, 2] * 8

        def gen(spec):
            engine = server_lib.build_engine(
                'debug', num_slots=2, max_seq_len=256,
                cache_mode='paged', spec_decode=spec)
            engine.start()
            try:
                return engine.generate(
                    prompt,
                    engine_lib.SamplingParams(max_new_tokens=16))
            finally:
                engine.stop()

        assert gen(4) == gen(0)

    def test_spec_sampling_and_topp_lower(self):
        """Round-4 sampling additions must lower on the real chip: the
        rejection-sampling spec verify (per-slot keys + categorical in
        a scan) and the combined top-k/top-p filter in the plain path."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        prompt = [5, 9, 2] * 8

        def gen(spec):
            engine = server_lib.build_engine(
                'debug', num_slots=2, max_seq_len=256,
                cache_mode='paged', spec_decode=spec)
            engine.start()
            try:
                return engine.generate(
                    prompt,
                    engine_lib.SamplingParams(
                        max_new_tokens=12, temperature=0.8,
                        top_k=16, top_p=0.8))
            finally:
                engine.stop()

        out_spec = gen(3)       # rejection-sampling verify path
        out_plain = gen(0)      # _sampling_filter in decode_n
        assert len(out_spec) == 12 and len(out_plain) == 12

    def test_chunked_prefill_lowers(self):
        """Chunked prefill's page-write path (insert w/o table install,
        suffix continuation per chunk) must lower and match."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        prompt = list(range(1, 101))

        def gen(chunk):
            engine = server_lib.build_engine(
                'debug', num_slots=2, max_seq_len=256,
                cache_mode='paged', prefill_chunk=chunk)
            engine.start()
            try:
                return engine.generate(
                    prompt,
                    engine_lib.SamplingParams(max_new_tokens=8))
            finally:
                engine.stop()

        assert gen(64) == gen(0)

    def test_quantized_engine_lowers(self):
        """int8 weight-only serving (QuantDense) must lower and decode
        on the chip."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        engine = server_lib.build_engine('debug', num_slots=2,
                                         max_seq_len=128,
                                         cache_mode='paged',
                                         quantize='int8')
        engine.start()
        try:
            out = engine.generate(
                [1, 2, 3, 4, 5, 6, 7, 8],
                engine_lib.SamplingParams(max_new_tokens=4))
            assert len(out) == 4
        finally:
            engine.stop()

    def test_int8_kv_engine_lowers(self):
        """int8 KV serving (quantized pools + in-kernel dequant read
        path + quantizing insert/append scatters) must lower and
        decode on the chip, agreeing with the fp engine's first
        token."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        prompt = [1, 2, 3, 4, 5, 6, 7, 8]

        def run(kv_dtype):
            engine = server_lib.build_engine('debug', num_slots=2,
                                             max_seq_len=128,
                                             cache_mode='paged',
                                             kv_dtype=kv_dtype)
            engine.start()
            try:
                return engine.generate(
                    prompt,
                    engine_lib.SamplingParams(max_new_tokens=4))
            finally:
                engine.stop()

        q8 = run('int8')
        fp = run('auto')
        assert len(q8) == 4
        assert q8[0] == fp[0]   # prefill is float either way

    def test_ragged_prefill_lowers(self):
        """The packed ragged admission path (segment-masked prefill +
        per-request src_off page scatters) must lower on the chip and
        match sequential admission byte-for-byte."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        prompts = [list(range(1, 20)), list(range(5, 55)),
                   list(range(7, 40))]
        base = server_lib.build_engine('debug', num_slots=4,
                                       max_seq_len=128,
                                       cache_mode='paged')
        model, params = base.model, base.params

        def run(**kw):
            engine = engine_lib.InferenceEngine(
                model, params, num_slots=4, max_seq_len=128,
                cache_mode='paged', **kw)
            qs = [engine.submit(
                p, engine_lib.SamplingParams(max_new_tokens=4))[1]
                for p in prompts]
            engine.start()
            try:
                outs = []
                for q in qs:
                    toks = []
                    while True:
                        t = q.get(timeout=300)
                        if t is None:
                            break
                        toks.append(t)
                    outs.append(toks)
                return outs, dict(engine.perf)
            finally:
                engine.stop()

        rag, perf = run()
        assert perf['ragged_dispatches'] >= 1
        seq, _ = run(batch_admission=False)
        assert rag == seq

    def test_prefix_cached_admission(self):
        """The prefix-cache suffix-prefill path (pool gather + dense
        continuation + offset page scatter) must lower on the chip and
        reproduce the uncached outputs."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import server as server_lib

        rng = np.random.default_rng(5)
        prompt = rng.integers(1, 250, 80).tolist()   # > 1 page of 64

        def run_twice(prefix_caching):
            engine = server_lib.build_engine(
                'debug', num_slots=2, max_seq_len=256,
                cache_mode='paged', prefix_caching=prefix_caching)
            engine.start()
            try:
                outs = []
                for _ in range(2):
                    outs.append(engine.generate(
                        prompt,
                        engine_lib.SamplingParams(max_new_tokens=4)))
                hits = engine.pool.prefix_stats['hit_pages']
                return outs, hits
            finally:
                engine.stop()

        cached, hits = run_twice(True)
        assert hits >= 1, 'second admission should share prefix pages'
        uncached, _ = run_twice(False)
        assert cached == uncached

    def test_lora_grouped_lowers(self):
        """The grouped-LoRA delta kernels (per-sequence gather and
        per-token grouped paths, docs/serving.md "Adapter fleet") must
        lower through Mosaic on the chip — not silently descend to the
        XLA floor — and match it numerically."""
        from skypilot_tpu.ops import dispatch
        from skypilot_tpu.ops import lora as lora_ops

        b, s, din, r, dout, n = 4, 256, 512, 8, 512, 4
        x = _rand(0, (b, s, din))
        a = _rand(1, (n, din, r))
        bb = _rand(2, (n, r, dout))
        # Slot 0 is the base model: its adapter rows are zero.
        a = a.at[0].set(0)
        bb = bb.at[0].set(0)
        key = jax.random.PRNGKey(3)
        scale_of = jnp.asarray([0.0, 2.0, 0.5, 1.0], jnp.float32)

        dispatch.reset_for_tests()
        jax.clear_caches()
        # Per-sequence ids [B]: the assigned-slot decode path.
        ids = jax.random.randint(key, (b,), 0, n)
        got = jax.jit(lora_ops.grouped_lora_delta)(
            x, a, bb, ids, scale_of[ids])
        ref = jax.jit(lora_ops._xla_gather)(x, a, bb, ids,
                                            scale_of[ids])
        assert dispatch.snapshot().get(lora_ops.OP) == 'pallas', \
            dispatch.snapshot()
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)

        dispatch.reset_for_tests()
        jax.clear_caches()
        # Per-token ids [B, S]: the mixed-adapter ragged-pack path.
        tids = jax.random.randint(key, (b, s), 0, n)
        got = jax.jit(lora_ops.grouped_lora_delta)(
            x, a, bb, tids, scale_of[tids])
        ref = jax.jit(lora_ops._xla_grouped)(x, a, bb, tids,
                                             scale_of[tids])
        assert dispatch.snapshot().get(lora_ops.OP) == 'pallas', \
            dispatch.snapshot()
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)


class TestCommsPlane:
    """On-chip comms plane gate (docs/observability.md "Comms plane"):
    the probe must measure real links and the census must count real
    SPMD collectives on the chip — the CPU suite can only prove the
    math, not the lowering."""

    def test_probe_and_census_on_chip(self, tmp_path, monkeypatch):
        from skypilot_tpu.parallel import comms_census
        from skypilot_tpu.parallel import comms_profile
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.models import llama
        from skypilot_tpu.train import trainer

        n = jax.device_count()
        if n < 2:
            pytest.skip('needs >= 2 devices for collectives')
        monkeypatch.setenv('SKYT_COMMS_CACHE',
                           str(tmp_path / 'comms.json'))
        comms_profile.reset_for_tests()
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(fsdp=n))
        profile, src = comms_profile.load_or_probe(
            mesh, payloads_mb=[1.0], iters=3, budget_s=240.0)
        assert src == 'probed'
        summ = comms_profile.summary(profile)
        assert summ.get('ici.all_reduce', {}).get('busbw_gbps', 0) > 0

        cfg = llama.CONFIGS['debug']
        model = llama.LlamaModel(cfg)
        tx = trainer.make_optimizer(trainer.TrainerConfig(
            warmup_steps=1, total_steps=4))
        sample = jnp.zeros((4, 64), jnp.int32)
        state, _ = trainer.create_sharded_state(
            model, tx, mesh, sample, jax.random.PRNGKey(0))
        step = trainer.make_train_step(model, tx, mesh, donate=False)
        data = {'tokens': sample, 'targets': sample}
        entries, source = comms_census.census_step(
            step, state, data, mesh=mesh, mode='compiled')
        assert source == 'hlo_compiled'
        assert entries, 'no collectives counted on a real sharded step'
        assert all(e.axes == ('fsdp',) for e in entries)
        rep = comms_census.report(
            entries, source, profile=profile,
            link_classes=comms_profile.axis_link_classes(mesh))
        assert rep['axes']['fsdp']['bytes'] > 0
        assert rep['axes']['fsdp']['seconds'] is not None

    def test_ici_beats_dcn_on_multislice(self, tmp_path, monkeypatch):
        """The physical claim the whole plane rests on: measured ICI
        bus bandwidth must exceed measured DCN bus bandwidth. Only a
        real multi-slice topology can answer."""
        from skypilot_tpu.parallel import comms_profile
        from skypilot_tpu.parallel import mesh as mesh_lib

        devices = jax.devices()
        slices = {getattr(d, 'slice_index', 0) for d in devices}
        if len(slices) < 2:
            pytest.skip('needs a real multi-slice topology '
                        '(device.slice_index)')
        monkeypatch.setenv('SKYT_COMMS_CACHE',
                           str(tmp_path / 'comms.json'))
        comms_profile.reset_for_tests()
        n_slices = len(slices)
        per_slice = len(devices) // n_slices
        mesh = mesh_lib.build_hybrid_mesh(
            mesh_lib.MeshSpec(fsdp=per_slice),
            mesh_lib.MeshSpec(dp=n_slices))
        profile, _src = comms_profile.load_or_probe(
            mesh, payloads_mb=[4.0], iters=3, budget_s=300.0)
        summ = comms_profile.summary(profile)
        ici = summ.get('ici.all_gather', {}).get('busbw_gbps', 0.0)
        dcn = summ.get('dcn.all_gather', {}).get('busbw_gbps', 0.0)
        assert ici > 0 and dcn > 0, summ
        assert ici > dcn, (
            f'ICI busbw {ici} GB/s should exceed DCN {dcn} GB/s')
