"""On-TPU smoke gate configuration.

Unlike tests/conftest.py (which forces a virtual CPU mesh so the suite
runs anywhere), this directory runs on whatever accelerator the machine
actually has. Every test here is marked `tpu` and self-skips off-TPU, so
`pytest tests_tpu/ -q` is safe in CPU-only CI and a real lowering gate on
a TPU machine.

Why it exists (VERDICT r2, Weak #2): CPU tests run Pallas kernels in
interpret mode, so a kernel the Mosaic compiler rejects can stay green on
CPU while crashing every real TPU training run. This gate compiles the
kernels on the chip before a snapshot ships.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'tpu: requires a real TPU device (skipped elsewhere)')


def _on_tpu() -> bool:
    """Probe for a WORKING TPU in a subprocess with a timeout: on a
    machine whose device tunnel is wedged, jax.devices() (and any first
    device op) can hang forever — the gate must SKIP, not hang the
    format.sh run."""
    import subprocess
    import sys
    try:
        out = subprocess.run(
            [sys.executable, '-c',
             'import jax, jax.numpy as jnp;'
             'x = jnp.ones((8, 8)) @ jnp.ones((8, 8));'
             'jax.block_until_ready(x);'
             'print(jax.devices()[0].platform)'],
            capture_output=True, text=True, timeout=120, check=False)
        return out.stdout.strip().endswith('tpu')
    except (subprocess.TimeoutExpired, OSError):
        return False


def pytest_collection_modifyitems(config, items):
    if _on_tpu():
        return
    skip = pytest.mark.skip(reason='no TPU device on this machine')
    for item in items:
        if 'tpu' in item.keywords:
            item.add_marker(skip)
