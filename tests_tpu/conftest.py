"""On-TPU smoke gate configuration.

Unlike tests/conftest.py (which forces a virtual CPU mesh so the suite
runs anywhere), this directory runs on whatever accelerator the machine
actually has. Every test here is marked `tpu` and self-skips off-TPU, so
`pytest tests_tpu/ -q` is safe in CPU-only CI and a real lowering gate on
a TPU machine.

Why it exists (VERDICT r2, Weak #2): CPU tests run Pallas kernels in
interpret mode, so a kernel the Mosaic compiler rejects can stay green on
CPU while crashing every real TPU training run. This gate compiles the
kernels on the chip before a snapshot ships.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'tpu: requires a real TPU device (skipped elsewhere)')


def _on_tpu() -> bool:
    """Probe for a WORKING TPU in a subprocess with a timeout: on a
    machine whose device tunnel is wedged, jax.devices() (and any first
    device op) can hang forever — the gate must SKIP, not hang the
    format.sh run."""
    import subprocess
    import sys
    try:
        proc = subprocess.Popen(
            [sys.executable, '-c',
             'import jax, jax.numpy as jnp;'
             'x = jnp.ones((8, 8)) @ jnp.ones((8, 8));'
             'jax.block_until_ready(x);'
             'print(jax.devices()[0].platform)'],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
    except OSError:
        return False
    try:
        out, _ = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        # Bounded post-kill wait too: a child stuck in an uninterruptible
        # device ioctl (D state) ignores SIGKILL — abandon it rather than
        # hang the gate in the unbounded wait subprocess.run would do.
        proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return False
    return (out or '').strip().endswith('tpu')


def pytest_collection_modifyitems(config, items):
    if _on_tpu():
        return
    skip = pytest.mark.skip(reason='no TPU device on this machine')
    for item in items:
        if 'tpu' in item.keywords:
            item.add_marker(skip)
